"""End-to-end train/predict behavior (reference: tests/python/test_basic.py)."""
import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.testing.data import make_binary, make_multiclass, make_regression


def test_binary_training_improves():
    X, y = make_binary(600, 8, seed=0)
    dtrain = xtb.DMatrix(X, label=y)
    res = {}
    bst = xtb.train(
        {"objective": "binary:logistic", "max_depth": 3, "eta": 0.5},
        dtrain, num_boost_round=20, evals=[(dtrain, "train")],
        evals_result=res, verbose_eval=False,
    )
    ll = res["train"]["logloss"]
    assert ll[-1] < ll[0] * 0.5
    p = bst.predict(dtrain)
    assert p.shape == (600,)
    assert 0 <= p.min() and p.max() <= 1
    acc = ((p > 0.5) == y).mean()
    assert acc > 0.9


def test_regression_rmse():
    X, y = make_regression(800, 10, seed=1)
    dtrain = xtb.DMatrix(X, label=y)
    res = {}
    xtb.train({"objective": "reg:squarederror", "max_depth": 4}, dtrain,
              num_boost_round=30, evals=[(dtrain, "train")], evals_result=res,
              verbose_eval=False)
    assert res["train"]["rmse"][-1] < 0.5 * np.std(y)


def test_multiclass_softprob():
    X, y = make_multiclass(600, 8, k=4, seed=2)
    dtrain = xtb.DMatrix(X, label=y)
    bst = xtb.train(
        {"objective": "multi:softprob", "num_class": 4, "max_depth": 3},
        dtrain, num_boost_round=10, verbose_eval=False,
    )
    p = bst.predict(dtrain)
    assert p.shape == (600, 4)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)
    assert (np.argmax(p, axis=1) == y).mean() > 0.85
    # softmax returns class ids
    bst2 = xtb.train(
        {"objective": "multi:softmax", "num_class": 4, "max_depth": 3},
        dtrain, num_boost_round=10, verbose_eval=False,
    )
    cls = bst2.predict(dtrain)
    assert cls.shape == (600,)
    assert set(np.unique(cls)).issubset({0.0, 1.0, 2.0, 3.0})


def test_deterministic_across_runs():
    X, y = make_binary(400, 6, seed=3)
    dtrain = xtb.DMatrix(X, label=y)
    params = {"objective": "binary:logistic", "max_depth": 4, "seed": 7,
              "subsample": 0.8, "colsample_bytree": 0.8}
    p1 = xtb.train(params, dtrain, 5, verbose_eval=False).predict(dtrain)
    dtrain2 = xtb.DMatrix(X, label=y)
    p2 = xtb.train(params, dtrain2, 5, verbose_eval=False).predict(dtrain2)
    np.testing.assert_array_equal(p1, p2)


def test_eval_on_holdout_and_early_stopping():
    X, y = make_binary(800, 8, seed=4)
    dtrain = xtb.DMatrix(X[:600], label=y[:600])
    dvalid = xtb.DMatrix(X[600:], label=y[600:])
    res = {}
    bst = xtb.train(
        {"objective": "binary:logistic", "max_depth": 2, "eta": 0.5},
        dtrain, num_boost_round=60,
        evals=[(dtrain, "train"), (dvalid, "valid")],
        early_stopping_rounds=5, evals_result=res, verbose_eval=False,
    )
    assert bst.best_iteration is not None
    assert bst.num_boosted_rounds() < 60  # stopped early


def test_base_margin_and_weights():
    X, y = make_regression(300, 5, seed=5)
    w = np.abs(np.random.default_rng(0).normal(size=300)).astype(np.float32)
    d = xtb.DMatrix(X, label=y, weight=w)
    bst = xtb.train({"objective": "reg:squarederror"}, d, 5, verbose_eval=False)
    p = bst.predict(d)
    assert np.isfinite(p).all()
    # output_margin == raw sums
    m = bst.predict(d, output_margin=True)
    np.testing.assert_allclose(p, m, rtol=1e-6)


def test_missing_values_dense():
    X, y = make_binary(500, 6, seed=6)
    X[np.random.default_rng(1).random(X.shape) < 0.3] = np.nan
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3}, d, 10,
                    verbose_eval=False)
    p = bst.predict(d)
    assert np.isfinite(p).all()
    assert ((p > 0.5) == y).mean() > 0.7


def test_csr_input():
    from xgboost_tpu.testing.data import make_sparse_csr

    M, y = make_sparse_csr(400, 15, density=0.3, seed=0)
    d = xtb.DMatrix(M, label=y)
    assert d.num_row() == 400 and d.num_col() == 15
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 3}, d, 10,
                    verbose_eval=False)
    p = bst.predict(d)
    assert np.isfinite(p).all()


def test_pandas_input():
    import pandas as pd

    X, y = make_regression(200, 4, seed=8)
    df = pd.DataFrame(X, columns=[f"col{i}" for i in range(4)])
    d = xtb.DMatrix(df, label=y)
    assert d.feature_names == ["col0", "col1", "col2", "col3"]
    bst = xtb.train({"objective": "reg:squarederror"}, d, 5, verbose_eval=False)
    assert np.isfinite(bst.predict(d)).all()


def test_pred_leaf_shape():
    X, y = make_binary(300, 5, seed=9)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3}, d, 4,
                    verbose_eval=False)
    leaves = bst.predict(d, pred_leaf=True)
    assert leaves.shape == (300, 4)
    assert leaves.dtype.kind in "iu" or leaves.dtype == np.int32


def test_iteration_range_and_slice():
    X, y = make_regression(300, 6, seed=10)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"objective": "reg:squarederror", "eta": 0.3}, d, 10,
                    verbose_eval=False)
    p5 = bst.predict(d, iteration_range=(0, 5))
    sliced = bst[0:5]
    np.testing.assert_allclose(sliced.predict(d), p5, rtol=1e-5)


def test_custom_objective():
    X, y = make_regression(300, 5, seed=11)
    d = xtb.DMatrix(X, label=y)

    def sq_obj(preds, dtrain):
        return preds - dtrain.get_label(), np.ones_like(preds)

    res = {}
    xtb.train({"objective": "reg:squarederror", "base_score": 0.0}, d, 10, obj=sq_obj,
              evals=[(d, "train")], evals_result=res, verbose_eval=False)
    assert res["train"]["rmse"][-1] < res["train"]["rmse"][0]


def test_cv_runs():
    X, y = make_binary(300, 5, seed=12)
    d = xtb.DMatrix(X, label=y)
    out = xtb.cv({"objective": "binary:logistic", "max_depth": 2}, d,
                 num_boost_round=5, nfold=3, as_pandas=False, verbose_eval=False)
    assert len(out["test-logloss-mean"]) == 5


def test_streamed_sparse_predict_bounded_memory():
    """Large sparse CSR predicts through fixed row windows with no full
    densification (reference: gpu_predictor.cu SparsePage loader split);
    values must equal the dense path exactly."""
    import scipy.sparse as sp

    rng = np.random.default_rng(0)
    F = 1000
    Xtr = rng.normal(size=(500, F)).astype(np.float32)
    ytr = (Xtr[:, 0] > 0).astype(np.float32)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3},
                    xtb.DMatrix(Xtr, label=ytr), 3, verbose_eval=False)

    R = 80_000  # R*F > _PREDICT_BUFFER_ELEMS -> streamed path
    nnz = 200_000
    rows = rng.integers(0, R, nnz)
    cols = rng.integers(0, F, nnz)
    vals = rng.normal(size=nnz).astype(np.float32)
    big = sp.csr_matrix((vals, (rows, cols)), shape=(R, F))
    d_big = xtb.DMatrix(big)
    assert bst._use_streamed_predict(d_big)
    p_big = bst.predict(d_big)
    assert p_big.shape == (R,) and np.all(np.isfinite(p_big))

    # exactness vs the dense path on a head slice
    head = 512
    d_head = xtb.DMatrix(big[:head].toarray())
    d_head_X = np.asarray(d_head.host_dense())
    d_head_X[d_head_X == 0.0] = np.nan  # CSR implicit zeros are missing
    p_head = bst.predict(xtb.DMatrix(d_head_X))
    np.testing.assert_array_equal(p_big[:head], p_head)


def test_feature_weights_bias_column_sampling():
    """feature_weights drives weighted column sampling (reference:
    src/common/random.h WeightedSamplingWithoutReplacement) — zero-weight
    features are never drawn, heavier features are drawn more often."""
    rng = np.random.default_rng(0)
    F = 6
    X = rng.normal(size=(300, F)).astype(np.float32)
    y = (X[:, 4] + X[:, 5] > 0).astype(np.float32)
    fw = np.array([0.0, 0.0, 1.0, 1.0, 4.0, 4.0], np.float32)
    d = xtb.DMatrix(X, label=y, feature_weights=fw)

    bst = xtb.train({"colsample_bytree": 0.5, "max_depth": 2},
                    d, 2, verbose_eval=False)
    counts = np.zeros(F)
    for it in range(300):
        fmask = bst._feature_masks(it, 0, F, fw)
        m = np.asarray(fmask(0, 1))[0]
        assert m.sum() == 3  # exactly max(1, 0.5*6) features
        counts += m
    assert counts[0] == 0 and counts[1] == 0
    assert counts[4] > counts[2] and counts[5] > counts[3]

    # wrong length / negative weights rejected
    import pytest
    with pytest.raises(ValueError):
        bst._feature_masks(0, 0, F, np.ones(F - 1))
    with pytest.raises(ValueError):
        bst._feature_masks(0, 0, F, -np.ones(F))


def test_device_sketch_path_covered(monkeypatch):
    """The accelerator sketch path (device sort + stride subsample) must
    stay CI-covered on the CPU backend via the force flag, and agree with
    the exact host grid within the subsample tolerance."""
    import os

    from xgboost_tpu.data.quantile import sketch_dense

    rng = np.random.default_rng(3)
    X = rng.normal(size=(3000, 4)).astype(np.float32)
    X[rng.random(X.shape) < 0.05] = np.nan

    host = sketch_dense(X, 32, use_device=True)  # CPU -> exact host grid
    monkeypatch.setenv("XTB_FORCE_DEVICE_SKETCH", "1")
    dev = sketch_dense(X, 32, use_device=True)   # forced device code path
    np.testing.assert_allclose(np.asarray(dev.cut_values),
                               np.asarray(host.cut_values),
                               rtol=1e-5, atol=1e-6)

    # subsampled regime (R > 2**19): quantiles stay close, extremes exact
    Xl = rng.normal(size=(1 << 19 | 4096, 2)).astype(np.float32)
    host_l = sketch_dense(Xl, 16, use_device=False)
    dev_l = sketch_dense(Xl, 16, use_device=True)
    hv = np.asarray(host_l.cut_values).reshape(2, -1)
    dv = np.asarray(dev_l.cut_values).reshape(2, -1)
    assert np.max(np.abs(hv - dv)) < 0.05  # ~1/sqrt(2**19) quantile noise
    np.testing.assert_allclose(hv[:, -1], dv[:, -1], rtol=1e-6)  # max exact
