"""ParallelFor pool determinism + plumbing (native/xtb_kernels.h,
native/xtb_simd.h, docs/native_threading.md).

The contract under test: every threaded native kernel produces output
BITWISE IDENTICAL to its sequential (nthread=1) SCALAR execution, for
every thread count AND every SIMD lane width — fuzzed here over
{scalar, vector} x nthread {1, 2, 8} across histogram (f32 + quantised
limbs), split scan, predict (raw + binned), the quantile sketch,
LambdaMART pair gradients, and TreeSHAP.  Plus: the nthread param
plumbing (params dict -> Context -> pool), the SIMD level plumbing
(env/set_simd -> both libraries), the `native.parallel_for` fault seam
(worker death -> correct results + respawn), and the pool telemetry
bridge.
"""
import os

import numpy as np
import pytest

import jax.numpy as jnp

from xgboost_tpu.utils import native

pytestmark = pytest.mark.skipif(not native.load_ffi(),
                                reason="FFI kernels unavailable")

NTHREADS = (1, 2, 8)
# the lane-width axis: scalar is the reference; "auto" resolves to the best
# detected ISA (avx2/neon) and MUST match scalar bitwise.  On hosts without
# any vector ISA both entries run scalar and the sweep degenerates safely.
SIMD_LEVELS = ("scalar", "auto")


@pytest.fixture(autouse=True)
def _default_pool_after():
    yield
    native.set_nthread(0)   # leave the default width for other tests
    native.set_simd("auto")  # and the default lane width


def _per_nthread(fn):
    """fn() once per (simd level, pool width); assert every run is
    bitwise-identical to the scalar nthread=1 reference."""
    native.set_simd(SIMD_LEVELS[0])
    native.set_nthread(NTHREADS[0])
    ref = fn()
    ref = ref if isinstance(ref, tuple) else (ref,)
    for simd in SIMD_LEVELS:
        native.set_simd(simd)
        for n in NTHREADS:
            if simd == SIMD_LEVELS[0] and n == NTHREADS[0]:
                continue  # the reference run
            native.set_nthread(n)
            got = fn()
            got = got if isinstance(got, tuple) else (got,)
            for r, g in zip(ref, got):
                np.testing.assert_array_equal(
                    np.asarray(g), np.asarray(r),
                    err_msg=(f"simd={simd} nthread={n} diverged from the "
                             f"scalar nthread=1 reference"))
    return ref


def test_hist_threaded_bitwise_fuzz():
    from xgboost_tpu.ops.histogram import build_histogram

    rng = np.random.default_rng(0)
    for R, F, B, N, stride, dt in ((4000, 7, 17, 4, 1, np.int32),
                                   (6000, 3, 33, 8, 2, np.uint8),
                                   (2500, 24, 64, 2, 1, np.int16)):
        bins = jnp.asarray(rng.integers(0, B + 1, size=(R, F)).astype(dt))
        gpair = jnp.asarray(rng.normal(size=(R, 2)), jnp.float32)
        node0 = N - 1
        pos = jnp.asarray(
            rng.integers(node0 - 1, node0 + 2 * N, size=R), jnp.int32)
        _per_nthread(lambda: build_histogram(
            bins, gpair, pos, node0=node0, n_nodes=N, n_bin=B,
            stride=stride))


def test_hist_q_threaded_bitwise_fuzz():
    from xgboost_tpu.ops.quantise import hist_accumulate_q

    rng = np.random.default_rng(1)
    R, F, B, N = 3000, 9, 16, 4
    bins = jnp.asarray(rng.integers(0, B + 1, size=(R, F)).astype(np.uint8))
    gq = jnp.asarray(rng.integers(-128, 128, size=(R, 2, 3)), jnp.int8)
    pos = jnp.asarray(rng.integers(N - 2, 3 * N, size=R), jnp.int32)
    _per_nthread(lambda: hist_accumulate_q(
        bins, gq, pos, jnp.asarray(N - 1, jnp.int32), n_nodes=N, n_bin=B))


def test_split_threaded_bitwise_fuzz():
    from xgboost_tpu.ops.split import SplitParams, evaluate_splits

    rng = np.random.default_rng(2)
    params = SplitParams(eta=0.3, gamma=0.0, min_child_weight=1.0,
                         lambda_=1.0, alpha=0.0, max_delta_step=0.0)
    for i, (N, F, B) in enumerate(((64, 5, 33), (3, 12, 17))):
        hist = rng.normal(size=(N, F, B, 2)).astype(np.float32)
        hist[..., 1] = np.abs(hist[..., 1])
        n_bins = rng.integers(1, B, size=F).astype(np.int32)
        for f in range(F):
            hist[:, f, n_bins[f]:] = 0.0
        totals = hist.sum(axis=(1, 2)) / max(F, 1)
        totals[..., 1] += 0.5
        if i == 1:
            # non-finite gradients upstream: inf prefix sums make
            # GR = inf - inf = NaN inside the gain eval — scalar and
            # vector must reject the SAME candidates (the vector body
            # must not quietly map NaN -> 0; pinned after review)
            hist[0, 0, 2, 0] = np.inf
            totals[0, 0] = np.inf
        out = _per_nthread(lambda: (lambda s: (s.gain, s.feature, s.bin,
                                               s.default_left, s.left_sum))(
            evaluate_splits(jnp.asarray(hist), jnp.asarray(totals),
                            jnp.asarray(n_bins), params)))
        assert np.isfinite(np.asarray(out[0])).any()


def test_predict_threaded_bitwise():
    import xgboost_tpu as xtb

    rng = np.random.default_rng(3)
    X = rng.normal(size=(5000, 8)).astype(np.float32)
    X[rng.random(X.shape) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0] * X[:, 1]) > 0).astype(np.float32)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 5,
                     "max_bin": 64}, xtb.DMatrix(X, label=y), 5,
                    verbose_eval=False)
    dm = xtb.DMatrix(X)
    _per_nthread(lambda: bst.predict(dm, output_margin=True))


def test_training_bitwise_nthread_invariant():
    """End to end: MODELS trained at different pool widths AND lane widths
    are identical byte for byte (the acceptance bar of the threading PR,
    extended to the SIMD axis in round 7)."""
    import xgboost_tpu as xtb

    rng = np.random.default_rng(4)
    X = rng.normal(size=(3000, 10)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)

    def train_raw():
        d = xtb.DMatrix(X, label=y)
        bst = xtb.train({"objective": "binary:logistic", "max_depth": 5},
                        d, 4, verbose_eval=False)
        return np.frombuffer(bytes(bst.save_raw("ubj")), np.uint8)

    raws = {}
    for simd in SIMD_LEVELS:
        native.set_simd(simd)
        for n in (1, 2):
            native.set_nthread(n)
            raws[(simd, n)] = train_raw()
    ref_key = (SIMD_LEVELS[0], 1)
    for key, raw in raws.items():
        np.testing.assert_array_equal(
            raw, raws[ref_key],
            err_msg=f"model bytes at {key} diverged from {ref_key}")


def test_sketch_threaded_bitwise():
    rng = np.random.default_rng(5)
    vals = rng.normal(size=200_000).astype(np.float32)
    vals[rng.random(vals.size) < 0.01] = np.nan
    wts = rng.random(vals.size).astype(np.float32)
    qs = np.linspace(0.0, 1.0, 257)

    def run():
        s = native.StreamingQuantileSummary(budget=512)
        s.push(vals[:120_000], wts[:120_000])
        s.push(vals[120_000:], wts[120_000:])
        return s.query(qs), np.float64(s.total_weight())

    _per_nthread(run)


def test_lambdarank_threaded_bitwise():
    from xgboost_tpu.objective.ranking import _lambda_gradients_topk_native

    rng = np.random.default_rng(6)
    sizes = np.concatenate([rng.integers(1, 60, size=40), [1, 200]])
    gptr = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int32)
    R = int(gptr[-1])
    pred = jnp.asarray(rng.normal(size=R), jnp.float32)
    y = jnp.asarray(rng.integers(0, 5, size=R), jnp.float32)
    _per_nthread(lambda: _lambda_gradients_topk_native(
        pred, y, jnp.asarray(gptr), k=16, ndcg_weight=True, score_norm=True,
        group_norm=True))


def test_shap_threaded_bitwise_and_matches_host_walk():
    import xgboost_tpu as xtb
    from xgboost_tpu.interpret import (_Path, _expected_value, _tree_arrays,
                                       _tree_shap_recurse, shap_values_tree)

    rng = np.random.default_rng(7)
    X = rng.normal(size=(400, 6)).astype(np.float64)
    X[rng.random(X.shape) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0]) > 0).astype(np.float32)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 4},
                    xtb.DMatrix(X.astype(np.float32), label=y), 3,
                    verbose_eval=False)
    tree = bst.trees[-1]

    (got,) = _per_nthread(lambda: shap_values_tree(tree, X))

    # the native kernel is the f64 twin of the Python recursion — same ops
    # in the same order; compare against the walk directly
    t = _tree_arrays(tree)
    ev = _expected_value(t)
    maxd = tree.max_depth + 2
    R, F = X.shape
    ref = np.zeros((R, F + 1))
    for r in range(R):
        phi = np.zeros(F + 1)
        _tree_shap_recurse(t, X[r], phi, 0, _Path(maxd + 1), 0, 1.0, 1.0, -1)
        phi[F] = ev
        ref[r] = phi
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-12, atol=1e-15)


def test_nthread_param_reaches_pool():
    """params["nthread"] -> Context -> native pool; env override; default."""
    import xgboost_tpu as xtb

    rng = np.random.default_rng(8)
    X = rng.normal(size=(300, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    xtb.train({"objective": "binary:logistic", "max_depth": 2, "nthread": 3},
              xtb.DMatrix(X, label=y), 1, verbose_eval=False)
    assert native.get_nthread() == 3

    old = os.environ.get("XGBOOST_TPU_NTHREAD")
    os.environ["XGBOOST_TPU_NTHREAD"] = "5"
    try:
        assert native.resolve_nthread(0) == 5
        assert native.resolve_nthread(2) == 2  # explicit beats env
    finally:
        if old is None:
            del os.environ["XGBOOST_TPU_NTHREAD"]
        else:
            os.environ["XGBOOST_TPU_NTHREAD"] = old
    assert native.resolve_nthread(0) == (os.cpu_count() or 1)


def test_dmatrix_nthread_scoped_to_construction():
    """DMatrix(nthread=) widths are CONSTRUCTION-scoped (the reference's
    semantics — omp_set_num_threads around the ingest): the pool returns to
    its prior width afterwards instead of leaking a global reconfigure."""
    import xgboost_tpu as xtb

    X = np.random.default_rng(9).normal(size=(50, 3)).astype(np.float32)
    before = native.set_nthread(3)
    assert before == 3
    xtb.DMatrix(X, nthread=1)
    assert native.get_nthread() == 3  # restored, not leaked


def test_simd_level_plumbing():
    """set_simd fans out to every loaded library; "auto" resolves to the
    detected ISA; forcing scalar always works; simd_info records
    provenance for the benches."""
    info = native.simd_info()
    assert info["detected"] in ("scalar", "avx2", "neon")
    assert native.set_simd("scalar") == "scalar"
    assert native.get_simd() == "scalar"
    eff = native.set_simd("auto")
    assert eff == info["detected"]
    assert native.simd_info()["lanes"] >= 1
    # an unavailable request resolves to the detected best, never errors
    other = "neon" if info["detected"] != "neon" else "avx2"
    assert native.set_simd(other) in (other, info["detected"])
    native.set_simd("auto")


def test_ellpack_native_bin_parity(monkeypatch):
    """The native ingestion kernel (xtb_ellpack_bin) is bitwise-equal to
    the XLA searchsorted formulation at every dtype, incl. NaN sentinel
    and top-bin clamp, across simd levels and thread counts."""
    from xgboost_tpu.data import ellpack
    from xgboost_tpu.data.quantile import sketch_dense

    rng = np.random.default_rng(12)
    for R, F, max_bin in ((3000, 7, 256), (1500, 4, 300)):
        X = rng.normal(size=(R, F)).astype(np.float32)
        X[rng.random(X.shape) < 0.1] = np.nan
        X[0, 0] = np.inf  # past-the-last-cut clamp
        cuts = sketch_dense(X, max_bin=max_bin)
        # the XLA reference: force the searchsorted formulation
        with monkeypatch.context() as m:
            m.setattr(native, "ellpack_bin_native",
                      lambda *a, **k: None)
            ref = np.asarray(ellpack.build_ellpack(X, cuts,
                                                   row_align=256).bins)
        for simd in SIMD_LEVELS:
            native.set_simd(simd)
            for n in (1, 8):
                native.set_nthread(n)
                page = ellpack.build_ellpack(X, cuts, row_align=256)
                np.testing.assert_array_equal(
                    np.asarray(page.bins), ref,
                    err_msg=f"simd={simd} nthread={n} vs XLA searchsorted")


def test_pool_fault_worker_death_recovers():
    """`native.parallel_for` seam (docs/reliability.md): a caller-applied
    fault kills one pool worker before its next region; the region must
    finish, results stay bitwise-correct, the pool respawns, and the fault
    is counted."""
    from xgboost_tpu.ops.histogram import build_histogram
    from xgboost_tpu.reliability import faults

    rng = np.random.default_rng(10)
    R, F, B, N = 4000, 8, 16, 4
    bins = jnp.asarray(rng.integers(0, B + 1, size=(R, F)).astype(np.uint8))
    gpair = jnp.asarray(rng.normal(size=(R, 2)), jnp.float32)
    pos = jnp.asarray(rng.integers(N - 2, 3 * N, size=R), jnp.int32)

    def hist():
        return np.asarray(build_histogram(bins, gpair, pos, node0=N - 1,
                                          n_nodes=N, n_bin=B))

    native.set_nthread(4)
    ref = hist()
    faults0 = native.pool_stats()["faults_total"]
    try:
        faults.install({"faults": [
            {"site": "native.parallel_for", "kind": "drop_connection"}]})
        native._NTHREAD = None  # force the next set_nthread through the seam
        native.set_nthread(4)
        np.testing.assert_array_equal(hist(), ref)
    finally:
        faults.clear()
    # the doomed worker consumes its retirement when it next wakes — that
    # can trail the region's completion (the caller drains small regions
    # before sleeping workers get scheduled), so poll rather than snapshot
    import time

    deadline = time.monotonic() + 5.0
    while (native.pool_stats()["faults_total"] <= faults0
           and time.monotonic() < deadline):
        time.sleep(0.05)
    assert native.pool_stats()["faults_total"] > faults0
    np.testing.assert_array_equal(hist(), ref)  # respawned pool still right


def test_pool_telemetry_series():
    from xgboost_tpu import telemetry
    from xgboost_tpu.ops.histogram import build_histogram

    rng = np.random.default_rng(11)
    R, F, B, N = 3000, 6, 16, 2
    bins = jnp.asarray(rng.integers(0, B + 1, size=(R, F)).astype(np.uint8))
    gpair = jnp.asarray(rng.normal(size=(R, 2)), jnp.float32)
    pos = jnp.asarray(rng.integers(0, N, size=R), jnp.int32)
    native.set_nthread(2)
    np.asarray(build_histogram(bins, gpair, pos, node0=0, n_nodes=N,
                               n_bin=B))
    stats = telemetry.native_pool.sync()
    assert stats["nthread"] == 2
    assert stats["kernels"]["hist"]["regions"] >= 1
    reg = telemetry.get_registry()
    assert reg.get("xtb_native_threads").get() == 2
    fam = reg.get("xtb_native_parallel_regions_total")
    assert fam.get("hist") >= 1
    text = telemetry.render_prometheus()
    assert "xtb_native_busy_seconds_bucket" in text
    # second sync folds only deltas (no double counting)
    before = fam.get("hist")
    telemetry.native_pool.sync()
    assert fam.get("hist") == before
