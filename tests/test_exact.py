"""tree_method="exact" — the grow_colmaker role (updater_colmaker.cc).

Reference test pattern: tests/python/test_updaters.py exercises exact on
small dense data and compares training quality across tree methods.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.metric import auc as _auc


def _data(seed=0, n=1500, f=8, sparsity=0.0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    if sparsity:
        X[rng.random((n, f)) < sparsity] = np.nan
    logit = np.nan_to_num(X[:, 0]) * 1.5 + np.nan_to_num(X[:, 1]) ** 2 - 1.0
    y = (logit + rng.normal(scale=0.5, size=n) > 0).astype(np.float32)
    return X, y


@pytest.mark.parametrize("sparsity", [0.0, 0.3])
def test_exact_learns(sparsity):
    X, y = _data(sparsity=sparsity)
    Xt, yt = _data(seed=5, sparsity=sparsity)
    params = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.3,
              "tree_method": "exact"}
    bst = xtb.train(params, xtb.DMatrix(X, label=y), 20, verbose_eval=False)
    a = _auc(bst.predict(xtb.DMatrix(Xt)), yt)
    assert a > 0.85, a


def test_exact_close_to_hist():
    """With max_bin large enough, hist approaches exact; quality must agree."""
    X, y = _data(seed=2)
    Xt, yt = _data(seed=7)
    out = {}
    for tm in ("exact", "hist"):
        params = {"objective": "binary:logistic", "max_depth": 5, "eta": 0.3,
                  "tree_method": tm, "max_bin": 512}
        bst = xtb.train(params, xtb.DMatrix(X, label=y), 15, verbose_eval=False)
        out[tm] = _auc(bst.predict(xtb.DMatrix(Xt)), yt)
    assert abs(out["exact"] - out["hist"]) < 0.02, out


def test_exact_regression_with_gamma_and_colsample():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(1200, 10)).astype(np.float32)
    yv = X[:, 0] * 2 + np.sin(X[:, 1]) + rng.normal(scale=0.1, size=1200)
    params = {"objective": "reg:squarederror", "max_depth": 5, "eta": 0.3,
              "tree_method": "exact", "gamma": 1.0, "colsample_bytree": 0.8,
              "subsample": 0.9}
    bst = xtb.train(params, xtb.DMatrix(X, label=yv.astype(np.float32)), 25,
                    verbose_eval=False)
    pred = bst.predict(xtb.DMatrix(X))
    rmse = float(np.sqrt(np.mean((pred - yv) ** 2)))
    assert rmse < 0.6, rmse
    # gamma pruning really engages: like the reference's TreePruner, only
    # leaf-pair parents are candidates — none of them may keep a < gamma split
    for t in bst.trees:
        lc, rc = t.left_children, t.right_children
        for nid in range(t.n_nodes):
            if lc[nid] == -1:
                continue
            if lc[lc[nid]] == -1 and lc[rc[nid]] == -1:
                assert t.loss_changes[nid] >= 1.0 - 1e-6


def test_exact_adaptive_quantile_leaves():
    rng = np.random.default_rng(4)
    X = rng.normal(size=(800, 6)).astype(np.float32)
    yv = (X[:, 0] + rng.normal(scale=0.2, size=800)).astype(np.float32)
    params = {"objective": "reg:absoluteerror", "max_depth": 4, "eta": 0.5,
              "tree_method": "exact"}
    bst = xtb.train(params, xtb.DMatrix(X, label=yv), 20, verbose_eval=False)
    mae = float(np.mean(np.abs(bst.predict(xtb.DMatrix(X)) - yv)))
    assert mae < 0.4, mae


def test_exact_model_roundtrip(tmp_path):
    X, y = _data(seed=9)
    params = {"objective": "binary:logistic", "max_depth": 4,
              "tree_method": "exact"}
    bst = xtb.train(params, xtb.DMatrix(X, label=y), 5, verbose_eval=False)
    p = tmp_path / "m.json"
    bst.save_model(str(p))
    bst2 = xtb.Booster(model_file=str(p))
    np.testing.assert_allclose(
        bst.predict(xtb.DMatrix(X)), bst2.predict(xtb.DMatrix(X)), rtol=1e-6)


def test_exact_missingness_signal_split():
    """colmaker's end-of-enumeration candidate: a constant-valued sparse
    column whose NaN pattern IS the label must still be splittable."""
    rng = np.random.default_rng(6)
    X = np.ones((400, 2), np.float32)
    X[:, 1] = rng.normal(size=400)
    miss = rng.random(400) < 0.5
    X[miss, 0] = np.nan
    y = miss.astype(np.float32)
    bst = xtb.train({"objective": "binary:logistic", "tree_method": "exact",
                     "max_depth": 3, "eta": 0.5},
                    xtb.DMatrix(X, label=y), 5, verbose_eval=False)
    t = bst.trees[0]
    assert t.n_nodes > 1, "missing-vs-present split was not found"
    assert t.split_indices[0] == 0
    p = bst.predict(xtb.DMatrix(X))
    assert float(np.mean((p > 0.5) == (y > 0.5))) > 0.99


def test_exact_max_leaves_bounds_unbounded_depth():
    X, y = _data(n=600)
    params = {"objective": "binary:logistic", "tree_method": "exact",
              "max_depth": 0, "max_leaves": 8, "eta": 0.5,
              "min_child_weight": 0.0}
    bst = xtb.train(params, xtb.DMatrix(X, label=y), 3, verbose_eval=False)
    for t in bst.trees:
        assert t.num_leaves <= 8, t.num_leaves


def test_exact_max_delta_step_clips_leaves():
    rng = np.random.default_rng(8)
    X = rng.normal(size=(500, 4)).astype(np.float32)
    y = (rng.random(500) < 0.02).astype(np.float32)  # unbalanced
    params = {"objective": "binary:logistic", "tree_method": "exact",
              "max_depth": 4, "eta": 1.0, "max_delta_step": 0.7}
    bst = xtb.train(params, xtb.DMatrix(X, label=y), 3, verbose_eval=False)
    for t in bst.trees:
        leaves = t.left_children == -1
        # leaf values = eta * clipped weight, |w| <= max_delta_step
        assert np.all(np.abs(t.split_conditions[leaves]) <= 0.7 + 1e-6)


def test_exact_extmem_raises():
    from xgboost_tpu.data.extmem import DataIter, ExtMemQuantileDMatrix

    X, y = _data(n=400)

    class It(DataIter):
        def __init__(self):
            super().__init__()
            self._i = 0

        def next(self, input_data):
            if self._i >= 2:
                return 0
            s = slice(self._i * 200, (self._i + 1) * 200)
            input_data(data=X[s], label=y[s])
            self._i += 1
            return 1

        def reset(self):
            self._i = 0

    d = ExtMemQuantileDMatrix(It(), max_bin=64)
    with pytest.raises(NotImplementedError):
        xtb.train({"tree_method": "exact", "objective": "binary:logistic"},
                  d, 2, verbose_eval=False)


def test_exact_unsupported_raise():
    X, y = _data(n=200)
    d = xtb.DMatrix(X, label=y)
    with pytest.raises((NotImplementedError, ValueError)):
        xtb.train({"tree_method": "exact", "monotone_constraints": "(1,0,0,0,0,0,0,0)",
                   "objective": "binary:logistic"}, d, 2, verbose_eval=False)
    with pytest.raises(ValueError):
        xtb.train({"tree_method": "exact", "grow_policy": "lossguide",
                   "objective": "binary:logistic"}, d, 2, verbose_eval=False)


from xgboost_tpu.testing import HAVE_ORACLE, ORACLE_PKG  # noqa: E402


@pytest.mark.skipif(not HAVE_ORACLE,
                    reason="oracle not built (run oracle/build_oracle.sh)")
def test_exact_oracle_parity(tmp_path):
    """Same data, tree_method=exact both sides: held-out AUC within 0.01 and
    identical root split feature on a clean signal."""
    X, y = _data(seed=11, n=2500)
    Xt, yt = _data(seed=12, n=2500)
    for name, arr in (("X", X), ("y", y), ("Xt", Xt), ("yt", yt)):
        np.save(tmp_path / f"{name}.npy", arr)
    params = {"objective": "binary:logistic", "max_depth": 5, "eta": 0.3,
              "eval_metric": "auc", "tree_method": "exact"}
    env = dict(os.environ, PYTHONPATH=ORACLE_PKG, JAX_PLATFORMS="cpu")
    code = f"""
import json, numpy as np, xgboost
X = np.load({str(tmp_path / 'X.npy')!r}); y = np.load({str(tmp_path / 'y.npy')!r})
Xt = np.load({str(tmp_path / 'Xt.npy')!r}); yt = np.load({str(tmp_path / 'yt.npy')!r})
ev = {{}}
bst = xgboost.train({params!r}, xgboost.DMatrix(X, label=y), 20,
                    evals=[(xgboost.DMatrix(Xt, label=yt), "t")],
                    evals_result=ev, verbose_eval=False)
root_feat = json.loads(bst.get_dump(dump_format="json")[0])["split"]
print(json.dumps({{"auc": ev["t"]["auc"][-1], "root": root_feat}}))
"""
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-3000:]
    res = json.loads(out.stdout.strip().splitlines()[-1])

    ev = {}
    bst = xtb.train(params, xtb.DMatrix(X, label=y), 20,
                    evals=[(xtb.DMatrix(Xt, label=yt), "t")],
                    evals_result=ev, verbose_eval=False)
    assert abs(ev["t"]["auc"][-1] - res["auc"]) < 0.01, (ev["t"]["auc"][-1], res)
    ours_root = f"f{bst.trees[0].split_indices[0]}"
    assert ours_root == res["root"], (ours_root, res["root"])


def test_exact_two_process_matches_single():
    """Distributed exact (updater_sync.cc role): every rank gathers the full
    row set, trees grow from identical inputs, rank 0 broadcasts — the
    2-worker model must equal the single-process model bitwise."""
    import threading

    from xgboost_tpu import collective

    rng = np.random.default_rng(7)
    X = rng.normal(size=(900, 5)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 0.2 * rng.normal(size=900)).astype(np.float32)

    params = {"objective": "reg:squarederror", "tree_method": "exact",
              "max_depth": 4, "eta": 0.5}
    single = xtb.train(params, xtb.DMatrix(X, label=y), 3, verbose_eval=False)
    want = "".join(single.get_dump(dump_format="json"))

    results, errors = {}, {}

    def worker(rank, world):
        try:
            with collective.CommunicatorContext(
                    dmlc_communicator="in-memory",
                    in_memory_world_size=world, in_memory_rank=rank,
                    in_memory_group="exact2"):
                _grp = collective._TLS.backend._group
                lo, hi = (0, 450) if rank == 0 else (450, 900)
                d = xtb.DMatrix(X[lo:hi], label=y[lo:hi])
                bst = xtb.train(params, d, 3, verbose_eval=False)
                results[rank] = "".join(bst.get_dump(dump_format="json"))
        except Exception as e:  # noqa: BLE001
            errors[rank] = e
            try:
                _grp.barrier.abort()
            except Exception:
                pass

    threads = [threading.Thread(target=worker, args=(r, 2), daemon=True)
               for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    assert not any(t.is_alive() for t in threads), "worker deadlocked"
    assert not errors, errors
    assert results[0] == results[1] == want
