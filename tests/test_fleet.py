"""Serving fleet: dispatcher policy, shared model store, wire protocol,
warm compile cache, and the multi-process no-loss contracts.

Single-process tiers exercise the unit seams (DispatchQueue shed/expiry
policy, ModelStore publish/snapshot parity, wire framing, program keys);
the multi-process tests pin the fleet-level contracts from
docs/serving.md "Fleet": bitwise parity with the in-process engine on
both request encodings, warm-cache cold-start at a fraction of
cold-cache, and replica death dropping nothing but (at most) nothing —
the in-flight batch reroutes to a live replica.
"""
import os
import signal
import time

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.reliability import faults
from xgboost_tpu.serving import (ModelStore, ServeConfig, ServingEngine,
                                 ServingFleet, SLOClass)
from xgboost_tpu.serving import wire
from xgboost_tpu.serving.fleet import DispatchQueue, FleetConfig, _Request
from xgboost_tpu.serving.warmcache import WarmProgramCache, program_key


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.clear()
    yield
    faults.clear()


def _train(seed=0, n=400, f=8, rounds=5, depth=4):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": depth,
                     "seed": seed}, xtb.DMatrix(X, label=y), rounds,
                    verbose_eval=False)
    return bst, X


def _req(rid, slo, model="m"):
    return _Request(rid, model, {"op": "predict", "id": rid}, b"", slo)


# =========================================================================
# DispatchQueue: SLO-ordered admission, shedding, expiry


def test_queue_priority_order_and_fifo_within_class():
    gold = SLOClass("gold", priority=2)
    free = SLOClass("free", priority=0)
    q = DispatchQueue(max_queue=16)
    order = []
    for rid, slo in [(1, free), (2, gold), (3, free), (4, gold)]:
        assert q.push(_req(rid, slo)) is None
    while True:
        r, _ = q.pop(time.monotonic())
        if r is None:
            break
        order.append(r.id)
    # gold first (FIFO within gold), then free (FIFO within free)
    assert order == [2, 4, 1, 3]


def test_queue_full_sheds_newest_lowest_priority():
    gold = SLOClass("gold", priority=2)
    free = SLOClass("free", priority=0)
    q = DispatchQueue(max_queue=2)
    assert q.push(_req(1, free)) is None
    assert q.push(_req(2, free)) is None
    # a gold request outranks: the NEWEST free request (id 2) is shed
    victim = q.push(_req(3, gold))
    assert victim is not None and victim.id == 2
    assert victim.state == "shed"
    # an equal-priority newcomer does not outrank anyone: it sheds itself
    victim = q.push(_req(4, free))
    assert victim is not None and victim.id == 4
    # queue still serves gold before the surviving free request
    r1, _ = q.pop(time.monotonic())
    r2, _ = q.pop(time.monotonic())
    assert [r1.id, r2.id] == [3, 1]


def test_queue_deadline_expires_in_queue():
    fast = SLOClass("fast", priority=1, deadline_s=0.005)
    slow = SLOClass("slow", priority=0, deadline_s=None)
    q = DispatchQueue(max_queue=8)
    q.push(_req(1, fast))
    q.push(_req(2, slow))
    time.sleep(0.02)
    r, expired = q.pop(time.monotonic())
    assert [e.id for e in expired] == [1]
    assert expired[0].state == "expired"
    assert r.id == 2  # the deadline-free request still serves


def test_queue_pop_skips_cancelled_futures():
    """A caller that timed out cancels its future; the queue must not
    hand the abandoned request to a replica."""
    slo = SLOClass()
    q = DispatchQueue(max_queue=8)
    r1, r2 = _req(1, slo), _req(2, slo)
    q.push(r1)
    q.push(r2)
    assert r1.future.cancel()
    r, _ = q.pop(time.monotonic())
    assert r.id == 2 and r1.state == "done"
    assert len(q) == 0


def test_queue_requeue_front_precedes_fifo():
    slo = SLOClass()
    q = DispatchQueue(max_queue=8)
    q.push(_req(1, slo))
    q.push(_req(2, slo))
    r, _ = q.pop(time.monotonic())
    assert r.id == 1
    q.requeue_front(r)  # rerouted in-flight work goes back to the FRONT
    r, _ = q.pop(time.monotonic())
    assert r.id == 1
    assert len(q) == 1


# =========================================================================
# wire protocol


def _socketpair():
    import socket

    a, b = socket.socketpair()
    return wire.configure(a), wire.configure(b)


def test_wire_raw_roundtrip_bitwise():
    X = np.random.default_rng(0).normal(size=(33, 7)).astype(np.float32)
    fields, payload = wire.encode_raw(X)
    a, b = _socketpair()
    try:
        wire.send_frame(a, dict(fields, op="predict", id=9), payload)
        hdr, body = wire.recv_frame(wire.reader(b))
        assert hdr["id"] == 9
        Y = wire.decode_matrix(hdr, body)
        np.testing.assert_array_equal(X, Y)
    finally:
        a.close()
        b.close()


def test_wire_large_payload_and_eof():
    import threading

    X = np.zeros((4096, 32), np.float32)  # > _INLINE_PAYLOAD: two sendalls
    fields, payload = wire.encode_raw(X)
    a, b = _socketpair()
    try:
        # 512KB overflows the socketpair buffer: send concurrently with
        # the receive (sendall blocks until the peer drains)
        tx = threading.Thread(target=wire.send_frame,
                              args=(a, fields, payload), daemon=True)
        tx.start()
        hdr, body = wire.recv_frame(b)
        tx.join(timeout=30)
        assert not tx.is_alive()
        assert wire.decode_matrix(hdr, body).shape == (4096, 32)
        a.close()
        with pytest.raises(wire.WireError):
            wire.recv_frame(b)  # EOF at frame boundary is still WireError
    finally:
        b.close()


def test_wire_recv_frame_slow_loris_bound():
    """A peer trickling a frame one byte per interval exhausts ONE
    cumulative frame budget (clocked from the first prefix byte), not an
    idle timeout reset on every byte."""
    import socket
    import threading

    X = np.zeros((4, 4), np.float32)
    fields, payload = wire.encode_raw(X)
    a, b = _socketpair()
    c, d = _socketpair()
    try:
        wire.send_frame(a, dict(fields, op="predict", id=1), payload)
        a.shutdown(socket.SHUT_WR)
        blob = b"".join(iter(lambda: b.recv(65536), b""))

        def _trickle():
            try:
                for i in range(len(blob)):
                    c.sendall(blob[i:i + 1])
                    time.sleep(0.02)
            except OSError:
                pass  # the reader gave up and closed: expected

        threading.Thread(target=_trickle, daemon=True).start()
        t0 = time.monotonic()
        with pytest.raises(wire.WireError, match="slow-loris"):
            wire.recv_frame(d, budget_s=0.3)
        assert time.monotonic() - t0 < 5.0
    finally:
        for s in (a, b, c, d):
            s.close()
    # the budget is a trickle bound, not a size bound: an intact frame
    # inside it still parses
    e, f = _socketpair()
    try:
        wire.send_frame(e, dict(fields, op="predict", id=2), payload)
        hdr, body = wire.recv_frame(f, budget_s=30.0)
        assert hdr["id"] == 2
        np.testing.assert_array_equal(wire.decode_matrix(hdr, body), X)
    finally:
        e.close()
        f.close()


def test_wire_arrow_roundtrip_parity():
    pa = pytest.importorskip("pyarrow")
    X = np.random.default_rng(1).normal(size=(50, 5)).astype(np.float32)
    batch = pa.RecordBatch.from_arrays(
        [pa.array(X[:, i]) for i in range(5)],
        names=[f"f{i}" for i in range(5)])
    fields, payload = wire.encode_arrow(batch)
    assert fields["enc"] == wire.ARROW
    Y = wire.decode_matrix(fields, bytes(payload))
    np.testing.assert_array_equal(X, Y)  # bitwise through the IPC stream


def test_wire_arrow_nulls_and_dictionary():
    pa = pytest.importorskip("pyarrow")
    from xgboost_tpu.data.arrow import ipc_batch_to_dense

    batch = pa.RecordBatch.from_arrays(
        [pa.array([1.0, None, 3.0], type=pa.float32()),
         pa.array([1, 2, 3], type=pa.int64())], names=["a", "b"])
    _, payload = wire.encode_arrow(batch)
    Y = ipc_batch_to_dense(bytes(payload))
    assert np.isnan(Y[1, 0]) and Y[2, 1] == 3.0  # nulls -> NaN, ints cast
    dict_batch = pa.RecordBatch.from_arrays(
        [pa.array(["x", "y", "x"]).dictionary_encode()], names=["c"])
    _, payload = wire.encode_arrow(dict_batch)
    with pytest.raises(ValueError, match="dictionary"):
        ipc_batch_to_dense(bytes(payload))


# =========================================================================
# ModelStore: one mmap copy, snapshot parity


def test_modelstore_publish_snapshot_parity(tmp_path):
    bst, X = _train(seed=3)
    store = ModelStore(str(tmp_path))
    v = store.publish("m", bst)
    assert v == 1 and store.entries() == [("m", 1)]
    snap = store.snapshot("m", device=False)
    from xgboost_tpu.serving.snapshot import InferenceSnapshot

    ref = InferenceSnapshot.from_booster(bst)
    for key, a in ref.stacked.items():
        b = snap.stacked[key]
        if a is None:
            assert b is None
        else:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert snap.num_features == ref.num_features
    assert snap.depth == ref.depth and snap.n_groups == ref.n_groups
    # the arena views are READ-ONLY mmaps: one host copy fleet-wide
    with pytest.raises(ValueError):
        np.asarray(snap.stacked["feat"])[0] = 0


def test_modelstore_engine_predict_bitwise(tmp_path):
    bst, X = _train(seed=4)
    store = ModelStore(str(tmp_path))
    store.publish("m", bst)
    eng = ServingEngine(ServeConfig(use_batcher=False))
    try:
        eng.add_model("ref", bst)
        ref = eng.predict("ref", X, direct=True)
        eng.registry.register_snapshot("m", store.snapshot("m"), 1)
        out = eng.predict("m", X, direct=True)
        np.testing.assert_array_equal(ref, out)
    finally:
        eng.close()


def test_modelstore_versioning_and_missing(tmp_path):
    bst, _ = _train(seed=5, rounds=2)
    bst2, _ = _train(seed=6, rounds=3)
    store = ModelStore(str(tmp_path))
    assert store.publish("m", bst) == 1
    assert store.publish("m", bst2) == 2
    assert store.latest_version("m") == 2
    assert store.snapshot("m", 1).n_trees != store.snapshot("m", 2).n_trees
    with pytest.raises(KeyError):
        store.snapshot("absent")


# =========================================================================
# warm program cache


def test_program_key_is_architecture_not_weights(tmp_path):
    # same architecture, different weights -> SAME program key (a
    # hot-swapped retrain warms instantly); different bucket/depth -> new
    bst_a, _ = _train(seed=7, rounds=3, depth=3)
    bst_b, _ = _train(seed=8, rounds=3, depth=3)
    store = ModelStore(str(tmp_path))
    store.publish("a", bst_a)
    store.publish("b", bst_b)
    sa = store.snapshot("a", device=False)
    sb = store.snapshot("b", device=False)
    assert program_key(sa, 64) == program_key(sb, 64)
    assert program_key(sa, 64) != program_key(sa, 128)
    bst_c, _ = _train(seed=7, rounds=3, depth=5)
    store.publish("c", bst_c)
    sc = store.snapshot("c", device=False)
    assert program_key(sa, 64) != program_key(sc, 64)


def test_warmcache_attach_and_reload(tmp_path):
    bst, X = _train(seed=9)
    store = ModelStore(str(tmp_path / "store"))
    store.publish("m", bst)
    snap = store.snapshot("m")
    warm = WarmProgramCache(str(tmp_path / "cache"))
    st = warm.attach(snap, (32, 64))
    assert st["compiled"] == 2 and st["hits"] == 0
    assert warm.save()
    # a second "replica" (fresh cache object + fresh snapshot) hits
    snap2 = store.snapshot("m")
    warm2 = WarmProgramCache(str(tmp_path / "cache"))
    st2 = warm2.attach(snap2, (32, 64))
    assert st2["hits"] == 2 and st2["compiled"] == 0
    # and the AOT program computes the same bits as the eager engine path
    eng = ServingEngine(ServeConfig(use_batcher=False))
    try:
        eng.add_model("ref", bst)
        ref = eng.predict("ref", X[:32], direct=True)
        out = np.asarray(snap2.aot_execute(X[:32], False))
        np.testing.assert_array_equal(ref, out[:, 0])
    finally:
        eng.close()


# =========================================================================
# fleet config


def test_fleet_config_validation():
    with pytest.raises(ValueError):
        FleetConfig(n_replicas=0)
    with pytest.raises(ValueError):
        FleetConfig(max_queue=0)
    cfg = FleetConfig(slo_classes={"t": SLOClass("gold", 2, 1.0)})
    assert cfg.resolve_slo("t").priority == 2
    assert cfg.resolve_slo("unknown").priority == 0
    assert cfg.resolve_slo(None).name == "default"
    with pytest.raises(ValueError):
        ServingFleet({}, n_replicas=1).start()  # no models


# =========================================================================
# multi-process fleet contracts (slow: real replica processes)


@pytest.fixture(scope="module")
def fleet_models(tmp_path_factory):
    d = tmp_path_factory.mktemp("fleet_models")
    bst_a, X = _train(seed=11, f=8, rounds=6, depth=4)
    bst_b, _ = _train(seed=12, f=8, rounds=4, depth=3)
    pa = str(d / "a.json")
    pb = str(d / "b.json")
    bst_a.save_model(pa)
    bst_b.save_model(pb)
    eng = ServingEngine(ServeConfig(use_batcher=False))
    eng.add_model("a", pa)
    eng.add_model("b", pb)
    ref_a = eng.predict("a", X, direct=True)
    ref_b = eng.predict("b", X, direct=True)
    eng.close()
    return {"a": pa, "b": pb, "X": X, "ref_a": ref_a, "ref_b": ref_b}


@pytest.mark.slow
def test_fleet_end_to_end_parity_and_reroute(fleet_models, tmp_path):
    X = fleet_models["X"]
    cache = str(tmp_path / "cache")
    with ServingFleet({"a": fleet_models["a"], "b": fleet_models["b"]},
                      n_replicas=2, cache_dir=cache, max_respawns=1,
                      warmup_buckets=(64, 512)) as fleet:
        assert fleet.alive_replicas() == 2
        # numpy path: bitwise the in-process engine
        np.testing.assert_array_equal(
            fleet.predict("a", X, timeout=60), fleet_models["ref_a"])
        np.testing.assert_array_equal(
            fleet.predict("b", X, timeout=60), fleet_models["ref_b"])
        # arrow path: bitwise too (zero-copy parity contract)
        try:
            import pyarrow as pa
        except ImportError:
            pa = None
        if pa is not None:
            batch = pa.RecordBatch.from_arrays(
                [pa.array(X[:, i]) for i in range(X.shape[1])],
                names=[f"f{i}" for i in range(X.shape[1])])
            np.testing.assert_array_equal(
                fleet.predict_arrow("a", batch, timeout=60),
                fleet_models["ref_a"])
        # unknown model surfaces the replica's error, typed
        with pytest.raises(KeyError):
            fleet.predict("nope", X[:4], timeout=60)
        # kill one replica mid-stream: nothing is lost — the dead
        # replica's in-flight batch reroutes, queued work drains on the
        # survivor (and later the respawn)
        victim = next(iter(fleet._replicas.values()))
        futs = [fleet.submit("a", X) for _ in range(24)]
        victim.proc.send_signal(signal.SIGKILL)
        for f in futs:
            np.testing.assert_array_equal(f.result(timeout=60),
                                          fleet_models["ref_a"])
        # respawn absorbs back to full strength
        deadline = time.monotonic() + 60
        while fleet.alive_replicas() < 2 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert fleet.alive_replicas() == 2
        np.testing.assert_array_equal(
            fleet.predict("b", X, timeout=60), fleet_models["ref_b"])
        # flight recorder on kill: the dispatcher dumped the SIGKILL'd
        # replica's last shipped ring + final snapshot driver-side (the
        # corpse itself never got the chance), and the failure record
        # points at it
        deadline = time.monotonic() + 30
        while (victim.label not in fleet.flight_dumps
               and time.monotonic() < deadline):
            time.sleep(0.05)
        dump_path = fleet.flight_dumps[victim.label]
        assert os.path.exists(dump_path)
        import json as _json

        dump = _json.load(open(dump_path))
        assert dump["label"] == victim.label
        assert any(e["name"] == "replica.start" for e in dump["events"])
        assert any(f["name"].startswith("xtb_")
                   for f in (dump["snapshot"] or {}).get("families", []))
        with fleet._cv:
            failure_tails = [t for (lb, _rc, t) in fleet._failures
                             if lb == victim.label]
        assert any("flight recorder" in t for t in failure_tails)


@pytest.mark.slow
def test_fleet_coldstart_warm_cache_faster(fleet_models, tmp_path):
    """The persistent-cache contract: a replica starting against a warm
    cache does a fraction of the cold warm-work (the >=10x claim lives in
    BENCH_SERVE.json; the test asserts the mechanism with slack for a
    noisy host: all programs hit, none compiled, and wall at most half)."""
    cache = str(tmp_path / "cache")
    buckets = (64, 512)
    kw = dict(n_replicas=1, cache_dir=cache, warmup_buckets=buckets)
    with ServingFleet({"a": fleet_models["a"]}, **kw) as fleet:
        cold = fleet.replica_info()[0]
    with ServingFleet({"a": fleet_models["a"]}, **kw) as fleet:
        warm = fleet.replica_info()[0]
    assert cold["aot_compiled"] == len(buckets) and cold["aot_hits"] == 0
    assert cold["cache_state"] == "cold"
    assert warm["aot_hits"] == len(buckets) and warm["aot_compiled"] == 0
    assert warm["cache_state"] == "warm"
    assert warm["warmup_s"] < cold["warmup_s"] / 2


def _stalled_first_request(fleet, model, X, seconds):
    """Submit one request whose dispatch-seam delay holds the lone replica
    'busy' (in_flight claimed, nothing on the wire) for ``seconds`` — the
    deterministic window the SLO tests stack the queue in.  Returns the
    (background-submitted) future; join via .result()."""
    import threading

    faults.install({"faults": [{"site": "fleet.dispatch", "kind": "delay",
                                "seconds": seconds, "at": 0, "times": 1}]})
    box = {}
    ev = threading.Event()

    def _bg():
        box["f"] = fleet.submit(model, X)  # blocks in the seam delay
        ev.set()

    t = threading.Thread(target=_bg, daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:  # wait until the stall claimed it
        with fleet._cv:
            busy = any(r.in_flight is not None
                       for r in fleet._replicas.values())
        if busy:
            break
        time.sleep(0.01)
    assert busy, "stalled request never claimed the replica"
    return box, ev


@pytest.mark.slow
def test_fleet_slo_deadline_and_dispatch_fault(fleet_models):
    X = fleet_models["X"][:32]
    classes = {"paid": SLOClass("paid", priority=2, deadline_s=30.0),
               "free": SLOClass("free", priority=0, deadline_s=0.05)}
    with ServingFleet({"a": fleet_models["a"]}, n_replicas=1,
                      warmup_buckets=(64,), slo_classes=classes) as fleet:
        # hold the replica for 1.5s; a free-tier request queued behind the
        # stall outlives its 50ms deadline and must expire with
        # TimeoutError, while the paid-tier request (queued later, higher
        # priority) still serves
        box, ev = _stalled_first_request(fleet, "a", X, 1.5)
        f_free = fleet.submit("a", X, tenant="free")
        f_paid = fleet.submit("a", X, tenant="paid")
        assert f_paid.result(timeout=60) is not None
        with pytest.raises(TimeoutError):
            f_free.result(timeout=60)
        ev.wait(timeout=60)
        assert box["f"].result(timeout=60) is not None
        faults.clear()
        # an exception at the dispatch seam fails that request only
        faults.install({"faults": [{"site": "fleet.dispatch",
                                    "kind": "exception",
                                    "message": "dispatch boom"}]})
        with pytest.raises(faults.FaultInjected):
            fleet.predict("a", X, timeout=60)
        faults.clear()
        np.testing.assert_array_equal(
            fleet.predict("a", fleet_models["X"], timeout=60),
            fleet_models["ref_a"])


@pytest.mark.slow
def test_fleet_extinct_fails_fast(fleet_models):
    """With the respawn budget spent and every replica dead, queued work
    fails with WorkerFailedError AND later submits fail fast instead of
    queueing into a permanent hang."""
    from xgboost_tpu.launcher import WorkerFailedError

    X = fleet_models["X"][:16]
    fleet = ServingFleet({"a": fleet_models["a"]}, n_replicas=1,
                         warmup_buckets=(64,), max_respawns=0).start()
    try:
        victim = next(iter(fleet._replicas.values()))
        victim.proc.send_signal(signal.SIGKILL)
        deadline = time.monotonic() + 60
        while not fleet._extinct and time.monotonic() < deadline:
            time.sleep(0.05)
        assert fleet._extinct
        with pytest.raises(WorkerFailedError, match="respawn budget"):
            fleet.predict("a", X, timeout=60)
    finally:
        fleet.close()


@pytest.mark.slow
def test_fleet_start_crash_fails_fast(fleet_models):
    """Replicas that crash during launch with no respawn budget must fail
    start() as soon as the fleet is extinct, not at ready_timeout_s."""
    from xgboost_tpu.launcher import WorkerFailedError

    t0 = time.monotonic()
    with pytest.raises(WorkerFailedError, match="replicas became ready"):
        ServingFleet({"a": fleet_models["a"]}, n_replicas=1,
                     max_respawns=0, platform="not_a_jax_backend",
                     ready_timeout_s=120).start()
    assert time.monotonic() - t0 < 60  # well under the ready timeout


@pytest.mark.slow
def test_fleet_queue_shed_under_pressure(fleet_models):
    """max_queue=2 with the replica stalled: a low-priority resident is
    shed to admit a higher class; an equal-priority newcomer sheds
    itself (FIFO fairness)."""
    from xgboost_tpu.serving.batcher import QueueFullError

    X = fleet_models["X"][:16]
    classes = {"gold": SLOClass("gold", priority=2),
               "free": SLOClass("free", priority=0)}
    with ServingFleet({"a": fleet_models["a"]}, n_replicas=1,
                      warmup_buckets=(64,), max_queue=2,
                      slo_classes=classes) as fleet:
        box, ev = _stalled_first_request(fleet, "a", X, 1.5)
        fillers = [fleet.submit("a", X, tenant="free") for _ in range(2)]
        gold = fleet.submit("a", X, tenant="gold")  # sheds a free filler
        shed = [f for f in fillers
                if isinstance(f.exception(timeout=60), QueueFullError)]
        assert len(shed) == 1 and shed[0] is fillers[1]  # newest free
        assert gold.result(timeout=60) is not None
        ev.wait(timeout=60)
        assert box["f"].result(timeout=60) is not None


# =========================================================================
# degraded-network survival: kill/respawn churn, breaker readmission via
# heartbeat probe, hedged dispatch neutrality (docs/reliability.md
# "Degraded networks")


def _counter(name, *labels):
    from xgboost_tpu.telemetry.registry import get_registry

    fam = get_registry().get(name)
    if fam is None:
        return 0.0
    if labels:
        for values, child in fam.collect():
            if values == tuple(labels):
                return float(child.value)
        return 0.0
    return sum(child.value for _v, child in fam.collect())


@pytest.mark.slow
def test_fleet_kill_respawn_churn_deterministic(fleet_models, tmp_path):
    """20 kill/respawn cycles: every request completes with the exact
    reference bits (zero drops), the fleet returns to full strength each
    cycle, and the respawn accounting is monotonic."""
    X = fleet_models["X"]
    ref = fleet_models["ref_a"]
    with ServingFleet({"a": fleet_models["a"]}, n_replicas=2,
                      cache_dir=str(tmp_path / "cache"), max_respawns=25,
                      warmup_buckets=(64,)) as fleet:
        np.testing.assert_array_equal(
            fleet.predict("a", X, timeout=120), ref)
        for cycle in range(20):
            with fleet._cv:
                victim = next(r for r in fleet._replicas.values()
                              if r.alive and r.proc is not None)
            futs = [fleet.submit("a", X) for _ in range(4)]
            victim.proc.send_signal(signal.SIGKILL)
            for fut in futs:  # nothing dropped, nothing wrong
                np.testing.assert_array_equal(fut.result(timeout=120), ref)
            deadline = time.monotonic() + 120
            while (fleet.alive_replicas() < 2
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            assert fleet.alive_replicas() == 2, f"cycle {cycle}"
            assert fleet._respawned == cycle + 1
        np.testing.assert_array_equal(
            fleet.predict("a", X, timeout=120), ref)


@pytest.mark.slow
def test_fleet_breaker_pong_probe_readmits_without_traffic(fleet_models):
    """The EWMA breaker ejects a laggy replica; with NO further traffic
    (a healthy sibling absorbs everything), the first heartbeat pong
    after cooldown is the half-open probe and readmits it — readmission
    must not depend on starving the healthy replicas first."""
    X = fleet_models["X"][:32]
    opened0 = _counter("xtb_net_breaker_transitions_total", "open")
    closed0 = _counter("xtb_net_breaker_transitions_total", "closed")
    with ServingFleet({"a": fleet_models["a"]}, n_replicas=2,
                      warmup_buckets=(64,), heartbeat_s=0.2,
                      heartbeat_timeout_s=10.0, breaker_latency_s=0.05,
                      breaker_cooldown_s=0.4) as fleet:
        ref = fleet.predict("a", X, timeout=60)
        # every replica0 frame (results and pongs alike) arrives 0.3s
        # late: the EWMA trips past the 50ms threshold immediately
        faults.install({"faults": [{"site": "wire.recv", "kind": "delay",
                                    "seconds": 0.3, "rank": "replica0",
                                    "times": 16}]})
        deadline = time.monotonic() + 30
        while (_counter("xtb_net_breaker_transitions_total", "open")
               == opened0 and time.monotonic() < deadline):
            np.testing.assert_array_equal(
                fleet.predict("a", X, timeout=60), ref)
        assert _counter("xtb_net_breaker_transitions_total",
                        "open") > opened0
        faults.clear()  # the link heals; no requests from here on
        deadline = time.monotonic() + 10
        while (_counter("xtb_net_breaker_transitions_total", "closed")
               == closed0 and time.monotonic() < deadline):
            time.sleep(0.05)
        assert _counter("xtb_net_breaker_transitions_total",
                        "closed") > closed0
        with fleet._cv:
            assert fleet._replicas["replica0"].breaker == "closed"
        np.testing.assert_array_equal(
            fleet.predict("a", X, timeout=60), ref)


@pytest.mark.slow
def test_fleet_hedged_dispatch_bitwise_neutral(fleet_models):
    """Hedging past the latency-quantile budget returns whichever copy
    settles first — and the bytes are the reference's either way (the
    twin shares the future; replicas are deterministic)."""
    X = fleet_models["X"][:48]
    with ServingFleet({"a": fleet_models["a"]}, n_replicas=2,
                      warmup_buckets=(64,), heartbeat_s=0.1,
                      heartbeat_timeout_s=30.0,
                      hedge_quantile=0.5, hedge_min_s=0.05) as fleet:
        ref = fleet.predict("a", X, timeout=60)
        for _ in range(9):  # latency history >= 8 arms the hedge budget
            np.testing.assert_array_equal(
                fleet.predict("a", X, timeout=60), ref)
        hedges0 = _counter("xtb_net_hedges_total")
        wins0 = _counter("xtb_net_hedge_wins_total")
        # replica0's rx path stalls 0.8s per frame: an in-flight request
        # ages past the ~ms p50 budget and hedges onto replica1
        faults.install({"faults": [{"site": "wire.recv", "kind": "delay",
                                    "seconds": 0.8, "rank": "replica0",
                                    "times": 12}]})
        deadline = time.monotonic() + 30
        while (_counter("xtb_net_hedges_total") == hedges0
               and time.monotonic() < deadline):
            np.testing.assert_array_equal(
                fleet.predict("a", X, timeout=60), ref)
        assert _counter("xtb_net_hedges_total") > hedges0
        assert _counter("xtb_net_hedge_wins_total") > wins0
        faults.clear()
        np.testing.assert_array_equal(
            fleet.predict("a", X, timeout=60), ref)


# =========================================================================
# DART + refresh/prune boosters through the fleet fast path (the dormant
# workload axes the lifecycle PR turns live)


def _fastpath_for(store, name, buckets=(64,)):
    """A replica-identical serving stack for one store entry: mmap
    snapshot -> AOT programs -> _FastPath (the exact path replica.py
    runs), without spawning processes."""
    from xgboost_tpu.serving.replica import _FastPath

    snap = store.snapshot(name)
    WarmProgramCache(None).attach(snap, buckets)
    return _FastPath(snap), snap


def test_fastpath_dart_dropout_free_parity(tmp_path):
    """DART inference is dropout-free: the _FastPath result (per-tree
    weights folded into the stacked values) must equal Booster.predict
    bitwise, and the continuation round-trips through model bytes."""
    rng = np.random.default_rng(31)
    X = rng.normal(size=(600, 8)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    params = {"booster": "dart", "objective": "binary:logistic",
              "rate_drop": 0.4, "one_drop": 1, "max_depth": 3, "seed": 5}
    bst = xtb.train(params, xtb.DMatrix(X, label=y), 8, verbose_eval=False)
    assert any(w != 1.0 for w in bst.tree_weights)  # dropout really fired

    store = ModelStore(str(tmp_path))
    store.publish("dart", bst)
    fp, snap = _fastpath_for(store, "dart")
    out = fp.run(X[:64], False)
    assert out is not None  # the AOT fast path took it, no engine fallback
    np.testing.assert_array_equal(out, bst.predict(xtb.DMatrix(X[:64])))

    # continuation round-trip: serialized bytes survive store archive and
    # continue training with the weights intact
    cont = xtb.train(params, xtb.DMatrix(X, label=y), 2,
                     verbose_eval=False, xgb_model=store.booster("dart"))
    assert cont.num_boosted_rounds() == bst.num_boosted_rounds() + 2
    v2 = store.publish("dart", cont)
    fp2, _ = _fastpath_for(store, "dart")
    np.testing.assert_array_equal(
        fp2.run(X[:64], False), cont.predict(xtb.DMatrix(X[:64])))
    assert store.model_bytes("dart", v2) == bytes(cont.serialize())


def test_fastpath_refresh_prune_same_arch_warms_instantly(tmp_path):
    """refresh/prune continuation (process_type=update) keeps the tree
    COUNT and stacked shapes: the arch-keyed program key is unchanged, so
    the hot-swapped version deserializes the incumbent's AOT programs
    instead of compiling (the instant-warm half of the swap design) —
    and the fast path serves it bitwise vs Booster.predict."""
    rng = np.random.default_rng(32)
    X = rng.normal(size=(800, 8)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 0.3 * rng.normal(size=800)).astype(np.float32)
    X2 = rng.normal(size=(800, 8)).astype(np.float32)
    y2 = (X2[:, 0] * X2[:, 1]).astype(np.float32)
    params = {"objective": "reg:squarederror", "max_depth": 4, "eta": 0.5}
    base = xtb.train(params, xtb.DMatrix(X, label=y), 4, verbose_eval=False)

    store = ModelStore(str(tmp_path))
    store.publish("m", base)
    # refresh the leaves against fresh rows via the continuation path
    refreshed = xtb.train(
        {**params, "process_type": "update", "updater": "refresh,prune"},
        xtb.DMatrix(X2, label=y2), base.num_boosted_rounds(),
        verbose_eval=False, xgb_model=store.booster("m"))
    assert len(refreshed.trees) == len(base.trees)  # structure preserved
    assert not np.array_equal(refreshed.predict(xtb.DMatrix(X2[:64])),
                              base.predict(xtb.DMatrix(X2[:64])))
    store.publish("m", refreshed)

    s1 = store.snapshot("m", 1)
    s2 = store.snapshot("m", 2)
    assert program_key(s1, 64) == program_key(s2, 64)  # same architecture

    # a warm cache populated by the incumbent serves the refresh with
    # hits only — zero compiles (the double-buffer instant-warm contract)
    cache = WarmProgramCache(str(tmp_path / "warm"))
    st1 = cache.attach(s1, (64,))
    cache.save()
    cache2 = WarmProgramCache(str(tmp_path / "warm"))
    st2 = cache2.attach(s2, (64,))
    assert st1["compiled"] >= 1
    assert st2 == {**st2, "hits": 1, "compiled": 0}

    from xgboost_tpu.serving.replica import _FastPath

    fp = _FastPath(s2)
    np.testing.assert_array_equal(fp.run(X2[:64], False),
                                  refreshed.predict(xtb.DMatrix(X2[:64])))


def test_fastpath_refresh_model_bytes_roundtrip(tmp_path):
    """The lifecycle continuation contract for the updaters: archived
    model bytes -> booster -> refresh -> serialize -> unserialize is a
    bitwise fixed point (what hot-swap publishes is exactly what a
    restarted fleet reloads)."""
    rng = np.random.default_rng(33)
    X = rng.normal(size=(500, 6)).astype(np.float32)
    y = (X[:, 0] - X[:, 1]).astype(np.float32)
    params = {"objective": "reg:squarederror", "max_depth": 3}
    base = xtb.train(params, xtb.DMatrix(X, label=y), 3, verbose_eval=False)
    refreshed = xtb.train(
        {**params, "process_type": "update", "updater": "refresh"},
        xtb.DMatrix(X, label=y), 3, verbose_eval=False, xgb_model=base)
    blob = bytes(refreshed.serialize())
    b2 = xtb.Booster()
    b2.unserialize(blob)
    assert bytes(b2.serialize()) == blob
    np.testing.assert_array_equal(b2.predict(xtb.DMatrix(X[:32])),
                                  refreshed.predict(xtb.DMatrix(X[:32])))
