"""Elastic training: survive worker loss at reduced world size, absorb
replacements at round boundaries (docs/reliability.md § Elastic training).

Quick tier: the regroup state machine runs on the in-memory thread
backend — no subprocess spawn — plus unit coverage of the shard map, the
versioned checkpoint format, the relay's stale-buffer flush, and the
launcher's failure attribution.  The real multi-process protocol (tracker
regroup, relay epochs, replacement absorption) is exercised by the
slow-tier tests here and at 4 workers by ``scripts/elastic_smoke.py`` in
the nightly suite.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu import collective
from xgboost_tpu.elastic import RegroupRequired, ShardMap
from xgboost_tpu.reliability import faults, latest_checkpoint
from xgboost_tpu.reliability.checkpoint import (CheckpointManager,
                                                CheckpointState, _decode)

PARAMS = {"objective": "binary:logistic", "max_depth": 2, "eta": 0.3,
          "max_bin": 16}


def _toy(n=900, f=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    return X, y


# ---------------------------------------------------------------------------
# ShardMap
# ---------------------------------------------------------------------------


def test_shard_map_create_rebalance_roundtrip():
    m = ShardMap.create(8, 4)
    # round-robin, every shard owned exactly once, deterministic
    assert m.assign == tuple(s % 4 for s in range(8))
    assert sorted(sum((m.shards_of(r) for r in range(4)), ())) == list(range(8))
    assert m == ShardMap.create(8, 4)

    shrunk = m.rebalance(3)
    assert shrunk.world == 3 and shrunk.num_shards == 8
    # the departed rank's shards are re-owned, none lost
    assert sorted(sum((shrunk.shards_of(r) for r in range(3)), ())) == list(range(8))
    # rebalance is a pure function: shrink-then-grow returns to the start
    assert shrunk.rebalance(4) == m

    assert ShardMap.from_dict(m.to_dict()) == m
    with pytest.raises(ValueError):
        ShardMap.create(2, 4)  # a rank would own no data
    with pytest.raises(ValueError):
        ShardMap.from_dict({"num_shards": 3, "world": 2, "assign": [0, 1]})


# ---------------------------------------------------------------------------
# Checkpoint format v2 + v1 fallback
# ---------------------------------------------------------------------------


def test_checkpoint_v2_carries_world_and_shard_map(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    smap = ShardMap.create(6, 3)
    mgr.save(CheckpointState(round=4, booster_bytes=b"model-bytes",
                             history={"train": {"logloss": [0.5, 0.4]}},
                             callback_state={}, world=3,
                             shard_map=smap.to_dict()))
    st = mgr.load_latest()
    assert st.round == 4 and st.booster_bytes == b"model-bytes"
    assert st.world == 3
    assert ShardMap.from_dict(st.shard_map) == smap


def _encode_v1(round_, booster, history):
    """The pre-elastic (PR 3) on-disk layout, byte for byte."""
    import hashlib
    import struct

    meta = json.dumps({"version": 1, "round": round_,
                       "booster_len": len(booster), "history": history,
                       "callback_state": {}}).encode()
    body = b"XTBCKPT1" + struct.pack(">I", len(meta)) + meta + booster
    return body + hashlib.sha256(body).digest()


def test_checkpoint_v1_backward_compat(tmp_path):
    """Pre-elastic checkpoints still load: world/shard_map read as None."""
    blob = _encode_v1(7, b"old-model", {"train": {"rmse": [1.0]}})
    st = _decode(blob)
    assert st.round == 7 and st.booster_bytes == b"old-model"
    assert st.world is None and st.shard_map is None

    # and through the manager's file path
    path = tmp_path / "ckpt_00000007.xtbckpt"
    path.write_bytes(blob)
    st = latest_checkpoint(str(tmp_path))
    assert st is not None and st.round == 7 and st.shard_map is None


def test_checkpoint_unknown_version_falls_back(tmp_path):
    """A future-format file is skipped (with a warning) in favor of the
    newest file this reader understands — the corruption-fallback path."""
    import hashlib
    import struct

    mgr = CheckpointManager(str(tmp_path))
    mgr.save(CheckpointState(round=3, booster_bytes=b"good", history={},
                             callback_state={}))
    meta = json.dumps({"version": 99, "round": 5, "booster_len": 1,
                       "history": {}, "callback_state": {}}).encode()
    body = b"XTBCKPT1" + struct.pack(">I", len(meta)) + meta + b"x"
    (tmp_path / "ckpt_00000005.xtbckpt").write_bytes(
        body + hashlib.sha256(body).digest())
    with pytest.warns(RuntimeWarning, match="version"):
        st = mgr.load_latest()
    assert st is not None and st.round == 3


# ---------------------------------------------------------------------------
# In-memory elastic shrink/absorb (the quick-tier regroup coverage)
# ---------------------------------------------------------------------------


def _elastic_worker(rank, world, group, ckpt_dir, rounds, num_shards,
                    results, errors, join=False, X=None, y=None):
    backend = None
    try:
        args = dict(dmlc_communicator="in-memory", in_memory_group=group)
        if join:
            args.update(in_memory_join=True, in_memory_join_timeout=120.0)
        else:
            args.update(in_memory_world_size=world, in_memory_rank=rank)
        with collective.CommunicatorContext(**args):
            backend = collective._TLS.backend

            def data_fn(smap, rank, world):
                rows = np.sort(np.concatenate(
                    [np.arange(s, len(X), smap.num_shards)
                     for s in smap.shards_of(rank)]))
                return xtb.DMatrix(X[rows], label=y[rows])

            cfg = xtb.ElasticConfig(data_fn, ckpt_dir,
                                    num_shards=num_shards)
            bst = xtb.train(PARAMS, None, rounds, elastic=cfg,
                            verbose_eval=False)
            results[rank if not join else f"join{rank}"] = bytes(
                bst.save_raw())
    except faults.FaultInjected:
        # the planned preemption: this worker departs the group
        if backend is not None:
            backend.leave()
    except Exception as e:  # noqa: BLE001
        errors[rank] = e
        try:
            backend._group.barrier.abort()
        except Exception:
            pass


def _run_inmemory_shrink(group, ckpt_dir, plan):
    X, y = _toy()
    results, errors = {}, {}
    faults.install(plan)
    try:
        threads = [threading.Thread(
            target=_elastic_worker,
            args=(r, 3, group, ckpt_dir, 5, 6, results, errors),
            kwargs=dict(X=X, y=y), daemon=True) for r in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads), "worker deadlocked"
    finally:
        faults.clear()
    assert not errors, errors
    return results


# NOTE: no `at` matcher here — thread workers share one process-global
# invocation counter, so rank+round are the right thread-safe matchers
# (the shrunken world has no rank 2, so the spec cannot re-fire).  The
# subprocess tests below DO pin `at`: there each worker counts alone, and
# a post-regroup worker re-running the same round at the victim's old
# rank must not be killed again.
_SHRINK_PLAN = {"faults": [{"site": "train.round", "kind": "exception",
                            "rank": 2, "round": 2}]}


def test_inmemory_elastic_shrink_finishes_at_reduced_world(tmp_path):
    """3 thread workers; rank 2 is preempted entering round 2; the two
    survivors regroup in-process, inherit its shards, and finish all 5
    rounds with identical model bytes — no restart."""
    results = _run_inmemory_shrink("el_shrink", str(tmp_path / "ck"),
                                   _SHRINK_PLAN)
    assert sorted(results) == [0, 1]  # rank 2 departed
    assert results[0] == results[1]
    bst = xtb.Booster()
    bst.load_model(results[0])
    assert bst.num_boosted_rounds() == 5

    st = latest_checkpoint(str(tmp_path / "ck"))
    assert st is not None and st.round == 5
    assert st.world == 2  # written after the shrink
    smap = ShardMap.from_dict(st.shard_map)
    assert smap.world == 2 and smap.num_shards == 6
    # the dead rank's shards are owned by survivors
    assert sorted(smap.shards_of(0) + smap.shards_of(1)) == list(range(6))


def test_inmemory_elastic_shrink_bitwise_reproducible(tmp_path):
    """The determinism contract: the same fault plan replayed gives
    bitwise-identical final model bytes."""
    a = _run_inmemory_shrink("el_rep_a", str(tmp_path / "a"), _SHRINK_PLAN)
    b = _run_inmemory_shrink("el_rep_b", str(tmp_path / "b"), _SHRINK_PLAN)
    assert a[0] == b[0], "elastic shrink is not reproducible"


def test_inmemory_elastic_absorb_replacement(tmp_path):
    """2 workers train; once checkpoints exist a replacement parks on the
    group and is absorbed at the next round boundary (world back to 3);
    everyone — including the replacement, which restores the shard map
    from the checkpoint — finishes with identical model bytes."""
    X, y = _toy()
    ckpt_dir = str(tmp_path / "ck")
    group = "el_absorb"
    results, errors = {}, {}
    threads = [threading.Thread(
        target=_elastic_worker,
        args=(r, 2, group, ckpt_dir, 6, 6, results, errors),
        kwargs=dict(X=X, y=y), daemon=True) for r in range(2)]
    for t in threads:
        t.start()
    # wait for the first committed checkpoint, then join mid-run
    deadline = time.monotonic() + 120
    while latest_checkpoint(ckpt_dir) is None:
        assert time.monotonic() < deadline, "no checkpoint appeared"
        time.sleep(0.02)
    joiner = threading.Thread(
        target=_elastic_worker,
        args=(9, None, group, ckpt_dir, 6, 6, results, errors),
        kwargs=dict(join=True, X=X, y=y), daemon=True)
    joiner.start()
    for t in threads + [joiner]:
        t.join(timeout=300)
    assert not any(t.is_alive() for t in threads + [joiner]), "deadlocked"
    assert not errors, errors
    assert sorted(map(str, results)) == ["0", "1", "join9"]
    vals = list(results.values())
    assert all(v == vals[0] for v in vals[1:])
    st = latest_checkpoint(ckpt_dir)
    assert st is not None and st.round == 6
    assert st.world == 3, "replacement was not absorbed before the end"
    assert ShardMap.from_dict(st.shard_map).world == 3


def test_inmemory_departure_while_peers_already_parked():
    """Regression: a member leaving AFTER its peers already entered the
    regroup barrier must re-trigger epoch formation — the parked
    survivors would otherwise wait out the full timeout."""
    from xgboost_tpu.collective import InMemoryBackend

    backends = [InMemoryBackend(3, r, group="el_parked") for r in range(3)]
    # rank 2 "is slow": 0 and 1 park in the regroup barrier first; only
    # rank 2's later departure can complete the formation
    out, errs = {}, {}

    def park(r):
        try:
            out[r] = backends[r].regroup(4)
        except Exception as e:  # noqa: BLE001
            errs[r] = e

    threads = [threading.Thread(target=park, args=(r,), daemon=True)
               for r in (0, 1)]
    for t in threads:
        t.start()
    time.sleep(0.3)  # both parked, waiting for rank 2
    backends[2].leave()  # departure must complete the formation
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads), \
        "parked survivors never unblocked after the departure"
    assert not errs, errs
    assert out[0] == (0, 2) and out[1] == (1, 2)

    # the NEW epoch must be usable: leave() aborts the OLD barrier, not
    # the one formation just installed (regression: Barrier.abort() is
    # permanent, so aborting the wrong one poisoned every later gather)
    gathered = {}

    def gather(r):
        try:
            gathered[r] = backends[r].allgather(np.asarray([r + 1.0]))
        except Exception as e:  # noqa: BLE001
            errs[r] = e

    threads = [threading.Thread(target=gather, args=(r,), daemon=True)
               for r in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads), "post-regroup gather hung"
    assert not errs, errs
    np.testing.assert_array_equal(gathered[0], [[1.0], [2.0]])
    np.testing.assert_array_equal(gathered[1], [[1.0], [2.0]])


# ---------------------------------------------------------------------------
# CollRelay: stale-buffer flush on a lost rank (partial-epoch regression)
# ---------------------------------------------------------------------------


def test_relay_flushes_lost_rank_partial_epoch():
    """A lost rank's pending per-seq contributions are flushed at regroup:
    the next epoch's gather contains ONLY fresh buffers — a dead worker's
    stale payload can never fold into a later allreduce."""
    from xgboost_tpu.tracker import (CollRelay, _recv_exact, recv_msg,
                                     send_msg)
    import socket as sk

    relay = CollRelay("127.0.0.1", 3, op_timeout=60.0, elastic=True)
    lost = []
    relay.on_worker_lost = lambda rank, msg: lost.append(rank)
    relay.start()

    def connect(rank, epoch):
        s = sk.create_connection(("127.0.0.1", relay.port), timeout=10)
        send_msg(s, {"cmd": "coll_join", "rank": rank, "epoch": epoch})
        return s

    def contribute(s, rank, data, out):
        send_msg(s, {"cmd": "coll", "seq": 0, "nbytes": len(data)})
        s.sendall(data)
        hdr = recv_msg(s, timeout=60.0)
        out[rank] = hdr
        if hdr and hdr.get("cmd") == "coll_result":
            out[rank, "buf"] = _recv_exact(s, int(hdr["nbytes"]),
                                           timeout=60.0)

    try:
        socks = {r: connect(r, 0) for r in range(3)}
        stale = {r: np.full(4, 10 + r, np.float32).tobytes()
                 for r in range(2)}
        out = {}
        workers = [threading.Thread(target=contribute,
                                    args=(socks[r], r, stale[r], out),
                                    daemon=True) for r in range(2)]
        for t in workers:
            t.start()
        time.sleep(0.3)       # both contributions parked in seq 0
        socks[2].close()      # rank 2 dies without ever contributing
        for t in workers:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in workers), "relay wedged"
        # blocked contributors were steered into the regroup, not failed
        assert out[0]["cmd"] == "coll_regroup", out[0]
        assert out[1]["cmd"] == "coll_regroup", out[1]
        assert lost == [2]

        # epoch 1 at world 2: same seq number, fresh buffers only
        relay.regroup(2, 1)
        fresh = {r: np.full(4, 70 + r, np.float32).tobytes()
                 for r in range(2)}
        socks2 = {r: connect(r, 1) for r in range(2)}
        out2 = {}
        workers = [threading.Thread(target=contribute,
                                    args=(socks2[r], r, fresh[r], out2),
                                    daemon=True) for r in range(2)]
        for t in workers:
            t.start()
        for t in workers:
            t.join(timeout=60)
        assert out2[0]["cmd"] == "coll_result"
        assert out2[1]["cmd"] == "coll_result"
        expect = fresh[0] + fresh[1]
        assert out2[0, "buf"] == expect, "stale epoch-0 buffer leaked in"
        assert out2[1, "buf"] == expect
    finally:
        relay.close()


def test_relay_rejects_stale_epoch_contribution():
    """A worker that raced the regroup (still tagged with the old epoch)
    is answered coll_regroup, not folded into the new epoch's gather."""
    from xgboost_tpu.tracker import CollRelay, recv_msg, send_msg
    import socket as sk

    relay = CollRelay("127.0.0.1", 2, op_timeout=30.0, elastic=True)
    relay.start()
    try:
        relay.regroup(2, 3)  # relay has moved on to epoch 3
        s = sk.create_connection(("127.0.0.1", relay.port), timeout=10)
        send_msg(s, {"cmd": "coll_join", "rank": 0, "epoch": 1})
        payload = b"\x00" * 8
        send_msg(s, {"cmd": "coll", "seq": 0, "nbytes": len(payload)})
        s.sendall(payload)
        hdr = recv_msg(s, timeout=30.0)
        assert hdr and hdr.get("cmd") == "coll_regroup", hdr
        s.close()
    finally:
        relay.close()


# ---------------------------------------------------------------------------
# Seam catalog
# ---------------------------------------------------------------------------


def test_elastic_seams_are_catalogued():
    assert "tracker.regroup" in faults.SEAMS
    assert "collective.regroup" in faults.SEAMS


def test_non_elastic_backend_refuses_regroup():
    with pytest.raises(RuntimeError, match="not elastic"):
        collective.CollBackend().regroup(0)
    assert collective.regroup_pending() is False


def test_regroup_required_is_runtime_error():
    # train() without elastic= must propagate, not swallow, the signal
    assert issubclass(RegroupRequired, RuntimeError)
    with pytest.raises(TypeError, match="dtrain"):
        xtb.train(PARAMS, None, 2)


def test_elastic_rejects_mismatched_checkpoint_directory(tmp_path):
    """A user CheckpointCallback on a different directory than the elastic
    config would checkpoint one place and recover from an empty other —
    refuse loudly instead of silently discarding progress on a death."""
    cb = xtb.CheckpointCallback(str(tmp_path / "a"))
    cfg = xtb.ElasticConfig(lambda smap, r, w: None, str(tmp_path / "b"))
    with pytest.raises(ValueError, match="must match"):
        xtb.train(PARAMS, None, 2, elastic=cfg, callbacks=[cb])


# ---------------------------------------------------------------------------
# Launcher failure attribution (satellite: stderr tails, not bare codes)
# ---------------------------------------------------------------------------


def _boom_worker(rank, world):
    if rank == 1:
        raise RuntimeError("deliberate boom from rank 1")
    time.sleep(300)  # survivor: only the abort fan-out ends this


def test_launcher_attaches_rank_and_stderr_tail():
    """A failing worker's raised error carries the spawn label, exit code,
    and the captured stderr tail with the real traceback — not a bare
    exit-code failure where the first cause is lost."""
    from xgboost_tpu.launcher import WorkerFailedError, run_distributed

    with pytest.raises(WorkerFailedError) as ei:
        run_distributed(_boom_worker, num_workers=2, platform="cpu",
                        timeout=300, rendezvous="tracker")
    err = ei.value
    assert err.failures, "no per-worker failure details"
    assert "stderr tail" in str(err)
    assert "deliberate boom from rank 1" in str(err)
    labels = [f[0] for f in err.failures]
    rcs = [f[1] for f in err.failures]
    assert all(rc != 0 for rc in rcs)
    assert len(labels) >= 1


def test_launcher_elastic_requires_tracker():
    from xgboost_tpu.launcher import run_distributed

    with pytest.raises(ValueError, match="elastic"):
        run_distributed(_boom_worker, num_workers=2, platform=None,
                        rendezvous="direct", elastic=True)


# ---------------------------------------------------------------------------
# Multi-process elastic (tracker protocol end to end)
# ---------------------------------------------------------------------------


def _mp_elastic_worker(rank, world, *, ckpt_dir, out_path, rounds,
                       num_shards):
    import numpy as np

    import xgboost_tpu as xtb

    rng = np.random.default_rng(0)
    X = rng.normal(size=(1200, 5)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)

    def data_fn(smap, rank, world):
        rows = np.sort(np.concatenate(
            [np.arange(s, len(X), smap.num_shards)
             for s in smap.shards_of(rank)]))
        return xtb.DMatrix(X[rows], label=y[rows])

    cfg = xtb.ElasticConfig(data_fn, ckpt_dir, num_shards=num_shards)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                     "eta": 0.3, "max_bin": 32}, None, rounds, elastic=cfg,
                    verbose_eval=False)
    from xgboost_tpu import collective as coll

    if coll.get_rank() == 0 and out_path:
        with open(out_path, "wb") as fh:
            fh.write(bytes(bst.save_raw()))


def _mp_run(tmp_path, tag, *, workers, kill_rank=None, max_respawns=0,
            rounds=6):
    import functools

    from xgboost_tpu.launcher import run_distributed

    ckpt = str(tmp_path / f"ck_{tag}")
    out = str(tmp_path / f"{tag}.ubj")
    plan = None
    if kill_rank is not None:
        plan = json.dumps({"faults": [
            {"site": "train.round", "kind": "kill", "rank": kill_rank,
             "round": 2, "at": 2, "exit_code": 43}]})
    run_distributed(
        functools.partial(_mp_elastic_worker, ckpt_dir=ckpt, out_path=out,
                          rounds=rounds, num_shards=2 * workers),
        num_workers=workers, platform="cpu", timeout=600,
        rendezvous="tracker", elastic=True, fault_plan=plan,
        max_respawns=max_respawns)
    return open(out, "rb").read(), latest_checkpoint(ckpt)


def test_two_process_elastic_shrink_to_single_worker(tmp_path):
    """Tracker-path acceptance at the smallest scale that exercises the
    whole protocol: 2 workers, rank 1 killed entering round 2, the single
    survivor regroups to world 1 and finishes all 6 rounds."""
    model, st = _mp_run(tmp_path, "shrink", workers=2, kill_rank=1)
    assert model and st is not None
    assert st.round == 6
    assert st.world == 1 and st.shard_map["world"] == 1
    bst = xtb.Booster()
    bst.load_model(model)
    assert bst.num_boosted_rounds() == 6


@pytest.mark.slow
def test_three_process_elastic_shrink_bitwise_reproducible(tmp_path):
    """3 workers, same deterministic kill plan run twice: both runs finish
    at world 2 with bitwise-identical model bytes."""
    m1, st1 = _mp_run(tmp_path, "rep1", workers=3, kill_rank=1)
    m2, st2 = _mp_run(tmp_path, "rep2", workers=3, kill_rank=1)
    assert st1.world == 2 and st2.world == 2
    assert m1 == m2, "elastic shrink is not bitwise reproducible"


@pytest.mark.slow
def test_three_process_elastic_absorbs_replacement(tmp_path):
    """3 workers, one killed, launcher respawns a replacement: it connects
    to the tracker, is absorbed at a round boundary with the shard map
    restored from the checkpoint, and the run finishes back at world 3."""
    import functools

    from xgboost_tpu.launcher import run_distributed

    ckpt = str(tmp_path / "ck_absorb")
    out = str(tmp_path / "absorb.ubj")
    plan = {"faults": [
        {"site": "train.round", "kind": "kill", "rank": 1, "round": 2,
         "at": 2, "exit_code": 43},
        # pace the rounds so the replacement's cold start lands mid-run
        {"site": "train.round", "kind": "delay", "seconds": 1.0,
         "times": 1000}]}
    run_distributed(
        functools.partial(_mp_elastic_worker, ckpt_dir=ckpt, out_path=out,
                          rounds=10, num_shards=6),
        num_workers=3, platform="cpu", timeout=600, rendezvous="tracker",
        elastic=True, fault_plan=json.dumps(plan), max_respawns=1)
    st = latest_checkpoint(ckpt)
    assert st is not None and st.round == 10
    assert st.shard_map["world"] == 3, "replacement was not absorbed"
    assert open(out, "rb").read()
