"""Sharded serving front-end: routing invariants, parity, and the
per-shard reliability contract (docs/serving.md "Sharded topology").

Fast tier pins the pure pieces — the shard hash (stability, tenant/model
sensitivity, spread), FleetConfig validation, and native-vs-Python wire
reader parity on a socketpair.  The slow multi-process tests pin the
contracts the sharding must not bend: same tenant/model routes to the
same shard across respawns, a sharded fleet is bitwise-identical to a
1-shard fleet, and a SIGKILL'd replica's in-flight window-1 batch
requeues within its OWN shard's replica group (the sibling shard never
sees a respawn).
"""
import os
import signal
import socket
import struct
import threading
import time
import zlib

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.reliability import faults
from xgboost_tpu.serving import ServeConfig, ServingEngine, ServingFleet
from xgboost_tpu.serving import wire
from xgboost_tpu.serving.fleet import FleetConfig, shard_of


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.clear()
    yield
    faults.clear()


def _train(seed=0, n=400, f=8, rounds=5, depth=4):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": depth,
                     "seed": seed}, xtb.DMatrix(X, label=y), rounds,
                    verbose_eval=False)
    return bst, X


# =========================================================================
# shard_of: the routing contract


def test_shard_of_stable_and_key_sensitive():
    # pure function of (tenant, model, n): identical across calls and
    # processes — which is WHY routing survives respawns
    assert shard_of("m", "t1", 4) == shard_of("m", "t1", 4)
    assert shard_of("m", "t1", 4) == zlib.crc32(b"t1\x00m") % 4
    # tenant and model are both part of the key
    keys = {(m, t): shard_of(m, t, 8)
            for m in ("a", "b") for t in ("t1", "t2", None)}
    assert len(set(keys.values())) > 1
    # None tenant and "" tenant collapse to the same key (the header
    # omits tenant entirely for both)
    assert shard_of("m", None, 8) == shard_of("m", "", 8)
    # n=1 is always shard 0 (the unsharded fleet's degenerate case)
    assert all(shard_of("m", f"t{i}", 1) == 0 for i in range(16))


def test_shard_of_spreads():
    hits = {shard_of("m", f"tenant{i}", 4) for i in range(64)}
    assert hits == {0, 1, 2, 3}


def test_fleet_config_shard_validation(monkeypatch):
    assert FleetConfig(n_replicas=4, n_shards=2).n_shards == 2
    with pytest.raises(ValueError, match="divisible"):
        FleetConfig(n_replicas=3, n_shards=2)
    with pytest.raises(ValueError, match="n_shards"):
        FleetConfig(n_replicas=4, n_shards=-1)
    # env default resolution (n_shards=0 = "use the env, default 1")
    monkeypatch.setenv("XGBOOST_TPU_FLEET_SHARDS", "2")
    assert FleetConfig(n_replicas=4).n_shards == 2
    monkeypatch.delenv("XGBOOST_TPU_FLEET_SHARDS")
    assert FleetConfig(n_replicas=4).n_shards == 1


# =========================================================================
# native wire reader: parity with the pure-Python path


def _send_frame_raw(sock, header: bytes, payload: bytes,
                    corrupt: bool = False):
    crc = zlib.crc32(payload, zlib.crc32(header))
    if corrupt:
        crc ^= 0xFF
    sock.sendall(struct.pack("<IQI", len(header), len(payload), crc)
                 + header + payload)


def test_native_reader_parity_with_python():
    """Both readers decode the same frames to the same bytes; the native
    path engages only on blocking sockets (the fleet's own config)."""
    lib_loaded = wire._native_lib() is not None
    payload = os.urandom(4096)
    for native in (True, False):
        a, b = socket.socketpair()
        try:
            if not native:
                b.settimeout(60)  # timeout => Python buffered reader
            rd = wire.reader(b)
            is_native = isinstance(rd, wire._NativeReader)
            assert is_native == (native and lib_loaded)
            _send_frame_raw(a, b'{"op": "x", "id": 7}', payload)
            hdr, body = wire.recv_frame(rd)
            assert hdr == {"op": "x", "id": 7}
            assert bytes(body) == payload
        finally:
            a.close()
            b.close()


@pytest.mark.skipif(wire._native_lib() is None,
                    reason="native wire library unavailable")
def test_native_reader_crc_and_kill_switch(monkeypatch):
    a, b = socket.socketpair()
    try:
        rd = wire.reader(b)
        assert isinstance(rd, wire._NativeReader)
        _send_frame_raw(a, b'{"op": "x"}', b"abc", corrupt=True)
        with pytest.raises(wire.WireCorruptError):
            wire.recv_frame(rd)
    finally:
        a.close()
        b.close()
    # the kill switch forces the Python reader for new connections
    monkeypatch.setenv("XGBOOST_TPU_WIRE_NATIVE", "0")
    monkeypatch.setattr(wire, "_NATIVE", None)
    a, b = socket.socketpair()
    try:
        assert not isinstance(wire.reader(b), wire._NativeReader)
    finally:
        a.close()
        b.close()
        monkeypatch.setattr(wire, "_NATIVE", None)


@pytest.mark.skipif(wire._native_lib() is None,
                    reason="native wire library unavailable")
def test_native_crc32_matches_zlib():
    import ctypes

    from xgboost_tpu.utils.native import load_wire

    lib = load_wire()
    rng = np.random.default_rng(0)
    for n in (0, 1, 7, 8, 9, 4096, 65537):
        blob = rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
        c_buf = (ctypes.c_ubyte * max(1, len(blob))).from_buffer_copy(
            blob or b"\x00")
        assert lib.xtb_wire_crc32(0, c_buf, len(blob)) == zlib.crc32(blob)
        # rolling: split at an odd offset
        k = n // 3
        part = lib.xtb_wire_crc32(0, c_buf, k)
        c_rest = (ctypes.c_ubyte * max(1, n - k)).from_buffer_copy(
            blob[k:] or b"\x00")
        assert lib.xtb_wire_crc32(part, c_rest, n - k) == zlib.crc32(blob)


# =========================================================================
# multi-process: sharded fleet contracts


@pytest.fixture(scope="module")
def shard_models(tmp_path_factory):
    d = tmp_path_factory.mktemp("shard_models")
    bst, X = _train(seed=21, f=8, rounds=5, depth=4)
    p = str(d / "a.json")
    bst.save_model(p)
    eng = ServingEngine(ServeConfig(use_batcher=False))
    eng.add_model("a", p)
    ref = eng.predict("a", X, direct=True)
    eng.close()
    return {"a": p, "X": X, "ref": ref}


@pytest.mark.slow
def test_sharded_bitwise_parity_and_routing(shard_models, tmp_path):
    """A 2-shard fleet answers bitwise-identically to a 1-shard fleet
    for every tenant, and each (tenant, model) key's requests land on
    exactly the shard_of shard (pinned via the per-shard request
    counters)."""
    X = shard_models["X"]
    ref = shard_models["ref"]
    cache = str(tmp_path / "cache")
    tenants = [f"t{i}" for i in range(6)] + [None]
    with ServingFleet({"a": shard_models["a"]}, n_replicas=2, n_shards=1,
                      cache_dir=cache, warmup_buckets=(64, 512)) as fleet:
        single = {t: fleet.predict("a", X, tenant=t, timeout=120)
                  for t in tenants}
    with ServingFleet({"a": shard_models["a"]}, n_replicas=4, n_shards=2,
                      cache_dir=cache, warmup_buckets=(64, 512)) as fleet:
        assert len(fleet._shards) == 2
        assert fleet.alive_replicas() == 4
        ins = fleet._ins
        for t in tenants:
            k = shard_of("a", t, 2)
            before = ins.shard_requests.get(str(k))
            other = ins.shard_requests.get(str(1 - k))
            out = fleet.predict("a", X, tenant=t, timeout=120)
            np.testing.assert_array_equal(out, ref)
            np.testing.assert_array_equal(out, single[t])
            assert ins.shard_requests.get(str(k)) > before
            assert ins.shard_requests.get(str(1 - k)) == other
        # shard-prefixed replica labels partition the registry
        labels = sorted(r for sh in fleet._shards
                        for r in sh._replicas)
        assert all(lab.startswith(("s0:", "s1:")) for lab in labels)


@pytest.mark.slow
def test_sharded_kill_requeues_within_own_shard(shard_models, tmp_path):
    """SIGKILL one shard's replica mid-stream: its in-flight window-1
    batch requeues within its OWN shard's replica group (zero loss,
    bitwise), the respawn carries the shard's label prefix, routing is
    unchanged, and the sibling shard never respawns."""
    X = shard_models["X"]
    ref = shard_models["ref"]
    # tenants pinned to shard 0 / shard 1 respectively
    t0 = next(f"t{i}" for i in range(64) if shard_of("a", f"t{i}", 2) == 0)
    t1 = next(f"t{i}" for i in range(64) if shard_of("a", f"t{i}", 2) == 1)
    with ServingFleet({"a": shard_models["a"]}, n_replicas=4, n_shards=2,
                      cache_dir=str(tmp_path / "cache"), max_respawns=2,
                      warmup_buckets=(64, 512)) as fleet:
        sh0, sh1 = fleet._shards
        np.testing.assert_array_equal(
            fleet.predict("a", X, tenant=t0, timeout=120), ref)
        with sh0._cv:
            victim = next(r for r in sh0._replicas.values()
                          if r.alive and r.proc is not None)
        futs = [fleet.submit("a", X, tenant=t0) for _ in range(6)]
        victim.proc.send_signal(signal.SIGKILL)
        for fut in futs:  # zero dropped, bitwise
            np.testing.assert_array_equal(fut.result(timeout=120), ref)
        deadline = time.monotonic() + 120
        while (sh0.alive_replicas() < 2 and time.monotonic() < deadline):
            time.sleep(0.05)
        assert sh0.alive_replicas() == 2
        assert sh0._respawned == 1 and sh1._respawned == 0
        with sh0._cv:
            respawn = [lab for lab in sh0._replicas
                       if "respawn" in lab]
        assert respawn and all(lab.startswith("s0:") for lab in respawn)
        # routing unchanged across the respawn: the same tenants still
        # land on the same shards (pure hash, no rebalancing)
        for tenant, shard in ((t0, sh0), (t1, sh1)):
            before = fleet._ins.shard_requests.get(shard._shard_label)
            np.testing.assert_array_equal(
                fleet.predict("a", X, tenant=tenant, timeout=120), ref)
            assert (fleet._ins.shard_requests.get(shard._shard_label)
                    > before)


@pytest.mark.slow
def test_sharded_lifecycle_broadcast_every_shard(shard_models, tmp_path):
    """Version lifecycle ops fan out: every shard loads/activates, and
    the sharded answer tracks the active version for every tenant."""
    from xgboost_tpu.serving import ModelStore

    store = ModelStore(str(tmp_path / "store"))
    bst, X = _train(seed=21, f=8, rounds=5, depth=4)
    store.publish("a", bst)
    store.set_active("a", 1)
    cont = xtb.train(dict(bst.params), xtb.DMatrix(
        X, label=(X[:, 0] > 0).astype(np.float32)), 2,
        verbose_eval=False, xgb_model=bst)
    store.publish("a", cont)
    with ServingFleet(store_dir=store.dir, n_replicas=4, n_shards=2,
                      cache_dir=str(tmp_path / "cache"),
                      warmup_buckets=(64, 512)) as fleet:
        v1 = {t: fleet.predict("a", X, tenant=t, timeout=120)
              for t in ("t0", "t1", "t2", "t3")}
        acks = fleet.load_version("a", 2)
        assert len(acks) == 4  # every replica in every shard acked
        fleet.activate_version("a", 2)
        assert fleet.active_version("a") == 2
        for t, old in v1.items():
            new = fleet.predict("a", X, tenant=t, timeout=120)
            assert not np.array_equal(new, old)
