"""Dask frontend choreography (xgboost_tpu/dask.py) without a dask install.

The stand-in client below implements the exact ``distributed.Client``
subset the frontend uses (scheduler_info / submit / gather) by running each
submitted task in a real subprocess — so the full train path (RabitTracker
rendezvous, per-worker communicator, distributed sketch + histogram
allreduce, rank-0 model marshaling) is exercised for real; only the
dask-collection partition mapping needs an actual dask cluster.
Reference pattern: tests/test_distributed/test_with_dask/test_with_dask.py
LocalCluster round-trips.
"""
import os
import pickle
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.dask import (DaskDMatrix, DaskXGBClassifier, predict, train)

_RUNNER = r"""
import pickle, sys
import jax
jax.config.update("jax_platforms", "cpu")
path = sys.argv[1]
with open(path, "rb") as fh:
    fn, args = pickle.load(fh)
out = fn(*args)
with open(path + ".out", "wb") as fh:
    pickle.dump(out, fh)
"""


class _SubprocessFuture:
    def __init__(self, proc, path):
        self.proc, self.path = proc, path

    def result(self, timeout=600):
        self.proc.wait(timeout=timeout)
        if self.proc.returncode != 0:
            raise RuntimeError(
                f"task failed:\n{open(self.path + '.log').read()[-3000:]}")
        with open(self.path + ".out", "rb") as fh:
            return pickle.load(fh)


class SubprocessClient:
    """distributed.Client stand-in: every submit() spawns a subprocess
    immediately (tasks must run concurrently — they rendezvous through the
    tracker); gather() joins them."""

    def __init__(self, n_workers=2):
        self._addrs = [f"tcp://127.0.0.1:{9000 + i}" for i in range(n_workers)]
        self._tmp = tempfile.mkdtemp(prefix="xtb_daskfake_")
        self._n = 0

    def scheduler_info(self):
        return {"workers": {a: {} for a in self._addrs}}

    def submit(self, fn, *args, workers=None, pure=False, **kw):
        path = os.path.join(self._tmp, f"task_{self._n}.pkl")
        self._n += 1
        with open(path, "wb") as fh:
            pickle.dump((fn, args), fh)
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + env.get("PYTHONPATH", "").split(os.pathsep))
        log = open(path + ".log", "w")
        proc = subprocess.Popen([sys.executable, "-c", _RUNNER, path],
                                stdout=log, stderr=subprocess.STDOUT, env=env)
        return _SubprocessFuture(proc, path)

    def gather(self, futures):
        return [f.result() for f in futures]


def _data(n=4000, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    X[rng.random(X.shape) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0]) * 1.5 + np.nan_to_num(X[:, 1]) > 0).astype(
        np.float32)
    return X, y


@pytest.mark.slow
def test_dask_train_matches_quality_and_predict_roundtrip():
    X, y = _data()
    client = SubprocessClient(n_workers=2)
    # pre-partitioned parts (the no-dask path): disjoint row shards
    parts = [(X[0::2], y[0::2]), (X[1::2], y[1::2])]
    d = DaskDMatrix(client, parts)
    assert d.num_partitions == 2

    out = train(client, {"objective": "binary:logistic", "max_depth": 4,
                         "eta": 0.3, "max_bin": 64}, d, 5,
                eval_train=True)
    bst = out["booster"]
    assert out["history"]["train"]["logloss"][-1] < \
        out["history"]["train"]["logloss"][0]

    # distributed predict over the same partitions == local predict on the
    # reassembled rows
    pd = predict(client, out, d)
    local = np.concatenate([
        bst.predict(xtb.DMatrix(X[0::2])), bst.predict(xtb.DMatrix(X[1::2]))])
    np.testing.assert_allclose(pd, local, rtol=1e-6)

    # quality close to single-process training on the union
    single = xtb.train({"objective": "binary:logistic", "max_depth": 4,
                        "eta": 0.3, "max_bin": 64},
                       xtb.DMatrix(X, label=y), 5, verbose_eval=False)
    err_d = np.mean((pd > 0.5) != np.concatenate([y[0::2], y[1::2]]))
    err_s = np.mean((single.predict(xtb.DMatrix(X)) > 0.5) != y)
    assert err_d <= err_s + 0.02, (err_d, err_s)


@pytest.mark.slow
def test_dask_sklearn_classifier():
    X, y = _data(n=2000)
    client = SubprocessClient(n_workers=2)
    parts = [(X[0::2], y[0::2]), (X[1::2], y[1::2])]
    clf = DaskXGBClassifier(client=client, n_estimators=4, max_depth=3,
                            max_bin=32)
    clf.fit(DaskDMatrix(client, parts))
    proba = clf.predict_proba(DaskDMatrix(client, parts))
    assert proba.shape == (2000, 2)
    pred = clf.predict(DaskDMatrix(client, parts))
    acc = np.mean(pred == np.concatenate([y[0::2], y[1::2]]))
    assert acc > 0.9


def test_dask_dmatrix_validation():
    client = SubprocessClient(n_workers=2)
    with pytest.raises(ValueError):
        DaskDMatrix(client, [])
    with pytest.raises(ValueError):
        # list input must pack labels into the parts
        DaskDMatrix(client, [(np.zeros((4, 2)), np.zeros(4))],
                    label=np.zeros(4))


@pytest.mark.slow
def test_dask_predict_partition_order_three_parts_two_workers():
    """3 partitions on 2 workers: worker A holds parts 0 and 2, worker B
    part 1 — predict() must still return rows in partition order, not
    worker-address order."""
    X, y = _data(n=3000)
    client = SubprocessClient(n_workers=2)
    thirds = [(X[0::3], y[0::3]), (X[1::3], y[1::3]), (X[2::3], y[2::3])]
    d = DaskDMatrix(client, thirds)
    out = train(client, {"objective": "binary:logistic", "max_depth": 3,
                         "eta": 0.3, "max_bin": 32}, d, 3)
    pd = predict(client, out, d)
    bst = out["booster"]
    local = np.concatenate([bst.predict(xtb.DMatrix(p[0])) for p in thirds])
    np.testing.assert_allclose(pd, local, rtol=1e-6)
