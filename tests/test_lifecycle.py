"""Online model lifecycle: continuation-train -> gate -> hot-swap.

Quick tiers cover the unit seams in isolation: the fresh-traffic window,
the model store's lifecycle surface (active version, archived model
bytes, arena checksum), the validation gate's accept/reject/direction
semantics, the registry retirement hook, and a full manager cycle
against an in-process stub fleet (ordering + durable-commit contracts
without processes).  The slow tier drives the real thing end to end:
a 2-replica fleet under sustained traffic, a continuation-trained
candidate passing the gate and hot-swapping with zero dropped requests,
a gate-rejected candidate and a mid-swap fault both leaving the
incumbent serving bit-identically, and rollback restoring the previous
version (docs/serving.md "Online model lifecycle").
"""
import os
import threading

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.lifecycle import (FreshWindow, GateConfig, LifecycleConfig,
                                   LifecycleManager, validate_candidate)
from xgboost_tpu.reliability import faults
from xgboost_tpu.reliability.checkpoint import CheckpointCallback
from xgboost_tpu.serving import ModelStore, ServingFleet
from xgboost_tpu.serving.modelstore import arena_checksum
from xgboost_tpu.serving.registry import ModelRegistry


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.clear()
    yield
    faults.clear()


def _data(seed=0, n=2000, f=8):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    return X, y


PARAMS = {"objective": "binary:logistic", "max_depth": 3,
          "eval_metric": "logloss", "seed": 7}


def _train(X, y, rounds=4, xgb_model=None, params=PARAMS):
    return xtb.train(params, xtb.DMatrix(X, label=y), rounds,
                     verbose_eval=False, xgb_model=xgb_model)


# =========================================================================
# FreshWindow


def test_fresh_window_sliding_bound():
    X, y = _data(n=200)
    w = FreshWindow(max_rows=90)
    for i in range(5):
        w.append(X[i * 40:(i + 1) * 40], y[i * 40:(i + 1) * 40])
    assert len(w) == 90
    Xw, yw, wt = w.arrays()
    # the NEWEST 90 rows survive (oldest fall off the front)
    np.testing.assert_array_equal(Xw, X[110:200])
    np.testing.assert_array_equal(yw, y[110:200])
    assert wt is None
    assert w.to_dmatrix().num_row() == 90
    w.clear()
    with pytest.raises(ValueError):
        w.arrays()


def test_fresh_window_weights_and_validation():
    X, y = _data(n=100)
    w = FreshWindow()
    w.append(X[:50], y[:50], weight=np.ones(50, np.float32))
    with pytest.raises(ValueError):  # weighted window stays weighted
        w.append(X[50:], y[50:])
    with pytest.raises(ValueError):  # length mismatch
        w.append(X[:10], y[:5])


def test_fresh_window_extmem_route():
    X, y = _data(n=256)
    w = FreshWindow()
    w.append(X, y)
    d = w.to_dmatrix(extmem_chunk_rows=64)
    assert d.num_row() == 256


# =========================================================================
# ModelStore lifecycle surface


def test_store_active_version_distinct_from_latest(tmp_path):
    X, y = _data()
    bst = _train(X, y)
    st = ModelStore(str(tmp_path))
    v1 = st.publish("m", bst)
    assert st.active_version("m") == v1  # no commit yet: falls to latest
    v2 = st.publish("m", bst)
    assert st.latest_version("m") == v2
    st.set_active("m", v1)
    # a later publish moves latest but NOT the committed serving version
    v3 = st.publish("m", bst)
    assert (st.latest_version("m"), st.active_version("m")) == (v3, v1)
    assert st.serving_entries() == [("m", v1)]
    with pytest.raises(KeyError):
        st.set_active("m", 99)  # unpublished


def test_store_model_bytes_roundtrip_and_checksum(tmp_path):
    X, y = _data(seed=3)
    bst = _train(X, y)
    st = ModelStore(str(tmp_path))
    v = st.publish("m", bst)
    # archived bytes ARE the serving model: serialize round-trip equality
    assert st.model_bytes("m", v) == bytes(bst.serialize())
    b2 = st.booster("m", v)
    d = xtb.DMatrix(X)
    np.testing.assert_array_equal(b2.predict(d), bst.predict(d))
    # publish-time checksum verifies off the mmapped arena
    assert st.checksum("m", v)
    assert st.verify_checksum("m", v)


def test_store_checksum_detects_corruption(tmp_path):
    X, y = _data(seed=4)
    st = ModelStore(str(tmp_path))
    v = st.publish("m", _train(X, y))
    arena = os.path.join(str(tmp_path), f"m.v{v}.arena")
    blob = bytearray(open(arena, "rb").read())
    blob[len(blob) // 2] ^= 0xFF  # one flipped bit in a field byte
    with open(arena, "wb") as fh:
        fh.write(blob)
    assert not st.verify_checksum("m", v)


def test_arena_checksum_deterministic_and_field_sensitive():
    fields = {"a": np.arange(8, dtype=np.float32),
              "b": np.arange(6, dtype=np.int32).reshape(2, 3)}
    assert arena_checksum(fields) == arena_checksum(dict(fields))
    mutated = {**fields, "a": fields["a"].copy()}
    mutated["a"][0] += 1
    assert arena_checksum(fields) != arena_checksum(mutated)


# =========================================================================
# Validation gate


def test_gate_accepts_improvement_rejects_regression():
    X, y = _data(seed=5)
    d = xtb.DMatrix(X, label=y)
    base = _train(X, y, rounds=4)
    cont = _train(X, y, rounds=3, xgb_model=base)  # more rounds: better fit
    dec = validate_candidate(cont, base, d, GateConfig())
    assert dec.accepted and dec.reason == "accepted"
    assert dec.metric == "logloss" and dec.improvement > 0
    # swapped roles: the "candidate" regresses and is rejected, with the
    # scores in the decision (the deterministic reject path)
    dec2 = validate_candidate(base, cont, d, GateConfig())
    assert not dec2.accepted and dec2.reason == "metric"
    assert dec2.improvement < 0 and "gate-logloss" in dec2.detail
    # identical candidate passes at min_improvement=0, fails above it
    assert validate_candidate(base, base, d, GateConfig()).accepted
    assert not validate_candidate(base, base, d,
                                  GateConfig(min_improvement=1e-9)).accepted


def test_gate_metric_direction_and_selection():
    X, y = _data(seed=6)
    params = dict(PARAMS, eval_metric=["auc", "logloss"])
    d = xtb.DMatrix(X, label=y)
    base = xtb.train(params, d, 3, verbose_eval=False)
    cont = xtb.train(params, d, 3, verbose_eval=False, xgb_model=base)
    # auc is higher-is-better by name inference
    dec = validate_candidate(cont, base, d, GateConfig(metric="auc"))
    assert dec.metric == "auc" and dec.accepted
    # default picks the LAST configured metric (EarlyStopping convention)
    assert validate_candidate(cont, base, d, GateConfig()).metric == "logloss"
    with pytest.raises(ValueError):
        validate_candidate(cont, base, d, GateConfig(metric="rmse"))


def test_gate_validate_seam_fires():
    X, y = _data(seed=7)
    d = xtb.DMatrix(X, label=y)
    base = _train(X, y)
    faults.install([{"site": "lifecycle.validate", "kind": "exception"}])
    with pytest.raises(faults.FaultInjected):
        validate_candidate(base, base, d)
    faults.clear()


# =========================================================================
# Registry retirement hook (satellite: LRU eviction + lifecycle retire
# share one code path)


def test_registry_retire_hook_shared_path():
    from xgboost_tpu.telemetry.registry import get_registry

    X, y = _data(seed=8)
    bst = _train(X, y, rounds=2)
    events = []
    reg = ModelRegistry(max_models=2)
    reg.add_retire_hook(lambda n, v, r, s: events.append((n, v, r)))
    fam = get_registry().get("xtb_serve_evicted_total")
    before = {k: c.get() for k, c in fam.collect()} if fam else {}

    reg.register("a", bst)
    reg.register("b", bst)
    reg.register("c", bst)          # capacity: LRU evicts "a"
    assert events == [("a", 1, "lru")]
    reg.remove("b")                 # explicit retirement, same hook
    assert events == [("a", 1, "lru"), ("b", 1, "retired")]
    assert reg.names() == ["c"]

    fam = get_registry().get("xtb_serve_evicted_total")
    after = {k: c.get() for k, c in fam.collect()}
    assert after.get(("a", "lru"), 0) - before.get(("a", "lru"), 0) == 1
    assert after.get(("b", "retired"), 0) - before.get(("b", "retired"), 0) == 1


def test_registry_pinned_never_lru_evicted_hook_still_fires_on_remove():
    X, y = _data(seed=9)
    bst = _train(X, y, rounds=2)
    events = []
    reg = ModelRegistry(max_models=2)
    reg.add_retire_hook(lambda n, v, r, s: events.append((n, v, r)))
    reg.register("live", bst)
    reg.pin("live", 1)
    reg.register("c1", bst)
    reg.register("c2", bst)   # evicts c1 (live is pinned)
    assert ("c1", 1, "lru") in events and all(e[0] != "live" for e in events)


# =========================================================================
# Manager against a stub fleet (ordering + durable-commit contracts,
# no processes)


class _StubFleet:
    """In-process stand-in recording the control-surface calls in order,
    mirroring ServingFleet's durable-commit semantics."""

    def __init__(self, store):
        self.store = store
        self.calls = []
        self._versions = dict(store.serving_entries())
        for name, v in store.serving_entries():
            store.set_active(name, v)

    @property
    def store_dir(self):
        return self.store.dir

    def active_version(self, model):
        return self._versions.get(model)

    def load_version(self, model, version, timeout=None, trace=None):
        self.calls.append(("load", model, int(version)))
        return [{"aot_hits": 0, "aot_compiled": 0}]

    def activate_version(self, model, version, timeout=None, trace=None):
        self.store.set_active(model, int(version))  # the durable commit
        self._versions[model] = int(version)
        self.calls.append(("activate", model, int(version)))
        return [{}]

    def retire_version(self, model, version, timeout=None, trace=None):
        self.calls.append(("retire", model, int(version)))
        return [{}]

    def set_shadow(self, model, version, fraction):
        self.calls.append(("set_shadow", model, int(version), fraction))

    def shadow_stats(self, model):
        return {"pairs": 5, "failures": 0, "mean_div": 0.0,
                "max_div": 0.0, "mean_ks": 0.0, "max_ks": 0.0}

    def clear_shadow(self, model):
        self.calls.append(("clear_shadow", model))
        return self.shadow_stats(model)


def _stub_pair(tmp_path, seed=10):
    X, y = _data(seed=seed, n=3000)
    base = _train(X[:2000], y[:2000])
    st = ModelStore(str(tmp_path / "store"))
    st.publish("m", base)
    return X, y, st, _StubFleet(st)


def test_manager_cycle_orders_load_shadow_activate_retire(tmp_path):
    X, y, st, fleet = _stub_pair(tmp_path)
    mgr = LifecycleManager(fleet, "m", config=LifecycleConfig(
        rounds_per_cycle=2, shadow_fraction=0.5, shadow_min_pairs=1))
    rep = mgr.run_cycle((X[2000:], y[2000:]),
                        eval_window=(X[:2000], y[:2000]))
    assert rep.swapped and rep.candidate_version == 2
    assert rep.decision.accepted and rep.shadow["pairs"] == 5
    ops = [c[0] for c in fleet.calls]
    assert ops == ["load", "set_shadow", "clear_shadow", "activate"]
    assert st.active_version("m") == 2
    # second cycle retires the version beyond the rollback window
    rep2 = mgr.run_cycle((X[2000:], y[2000:]),
                         eval_window=(X[:2000], y[:2000]))
    assert rep2.swapped and rep2.candidate_version == 3
    assert ("retire", "m", 1) in fleet.calls
    assert ("retire", "m", 2) not in fleet.calls  # rollback target stays
    assert {"train", "validate", "publish", "load", "activate"} <= set(
        rep2.timings)


def test_manager_reject_leaves_active_untouched(tmp_path):
    X, y, st, fleet = _stub_pair(tmp_path, seed=11)
    mgr = LifecycleManager(fleet, "m", config=LifecycleConfig(
        rounds_per_cycle=1, gate=GateConfig(min_improvement=1e9)))
    rep = mgr.run_cycle((X[2000:], y[2000:]))
    assert not rep.swapped and rep.decision.reason == "metric"
    assert rep.candidate_version is None  # rejected BEFORE publish
    assert st.active_version("m") == 1 and fleet.calls == []


def test_manager_validate_fault_is_deterministic_reject(tmp_path):
    X, y, st, fleet = _stub_pair(tmp_path, seed=12)
    mgr = LifecycleManager(fleet, "m",
                           config=LifecycleConfig(rounds_per_cycle=1))
    faults.install([{"site": "lifecycle.validate", "kind": "exception"}])
    rep = mgr.run_cycle((X[2000:], y[2000:]))
    assert not rep.swapped and rep.decision.reason == "fault"
    assert st.active_version("m") == 1 and fleet.calls == []


def test_manager_swap_fault_aborts_before_commit(tmp_path):
    X, y, st, fleet = _stub_pair(tmp_path, seed=13)
    mgr = LifecycleManager(fleet, "m",
                           config=LifecycleConfig(rounds_per_cycle=1))
    faults.install([{"site": "lifecycle.swap", "kind": "exception"}])
    rep = mgr.run_cycle((X[2000:], y[2000:]))
    assert not rep.swapped and rep.decision.reason == "fault"
    assert rep.candidate_version == 2      # published but never activated
    assert st.active_version("m") == 1     # commit never happened
    ops = [c[0] for c in fleet.calls]
    assert "activate" not in ops
    assert ("retire", "m", 2) in fleet.calls  # candidate cleaned off replicas


def test_manager_rollback_requires_a_swap(tmp_path):
    X, y, st, fleet = _stub_pair(tmp_path, seed=14)
    mgr = LifecycleManager(fleet, "m",
                           config=LifecycleConfig(rounds_per_cycle=1))
    with pytest.raises(RuntimeError):
        mgr.rollback()
    rep = mgr.run_cycle((X[2000:], y[2000:]),
                        eval_window=(X[:2000], y[:2000]))
    assert rep.swapped
    assert mgr.rollback() == 1
    assert st.active_version("m") == 1


def test_manager_continuation_resumes_from_checkpoint(tmp_path):
    """A continuation killed mid-cycle resumes from its newest checkpoint
    and lands on the SAME bytes as an uninterrupted continuation (the
    crash-safety contract; resume_from > xgb_model precedence)."""
    X, y, st, fleet = _stub_pair(tmp_path, seed=15)
    base = st.booster("m", 1)
    dwin = xtb.DMatrix(X[2000:], label=y[2000:])
    full = xtb.train(PARAMS, xtb.DMatrix(X[2000:], label=y[2000:]), 4,
                     verbose_eval=False, xgb_model=base)

    mgr = LifecycleManager(fleet, "m", config=LifecycleConfig(
        rounds_per_cycle=4, checkpoint_dir=str(tmp_path / "ckpt")))
    # simulate the interrupted first attempt: 2 of 4 rounds, checkpointing
    # into the cycle's directory, then "crash"
    ckpt_dir = mgr._ckpt_dir(1)
    xtb.train(PARAMS, dwin, 2, verbose_eval=False, xgb_model=base,
              callbacks=[CheckpointCallback(ckpt_dir)])
    # the retry resumes from round 6 (base 4 + 2) and finishes at 8
    resumed = mgr.continue_training((X[2000:], y[2000:]))
    assert resumed.num_boosted_rounds() == 8
    assert bytes(resumed.serialize()) == bytes(full.serialize())


def test_manager_rejected_cycle_consumes_checkpoints(tmp_path):
    """A finished continuation's checkpoints are consumed even when the
    gate REJECTS the candidate: the next cycle must train on its own
    window (resuming a completed stale continuation would re-propose the
    same rejected candidate forever, and the loop would stop learning)."""
    from xgboost_tpu.reliability.checkpoint import latest_checkpoint

    X, y, st, fleet = _stub_pair(tmp_path, seed=16)
    mgr = LifecycleManager(fleet, "m", config=LifecycleConfig(
        rounds_per_cycle=2, checkpoint_dir=str(tmp_path / "ckpt"),
        gate=GateConfig(min_improvement=1e9)))
    rep = mgr.run_cycle((X[2000:], y[2000:]))
    assert not rep.swapped
    assert latest_checkpoint(mgr._ckpt_dir(1)) is None  # consumed
    # the follow-up continuation genuinely trains on a DIFFERENT window:
    # its bytes equal a fresh continuation on that window, not the
    # rejected candidate's
    X2, y2 = _data(seed=61, n=500)
    cand = mgr.continue_training((X2, y2))
    fresh = xtb.train(PARAMS, xtb.DMatrix(X2, label=y2), 2,
                      verbose_eval=False, xgb_model=st.booster("m", 1))
    assert bytes(cand.serialize()) == bytes(fresh.serialize())


# =========================================================================
# Real fleet, end to end (slow: multi-process)


@pytest.mark.slow
def test_lifecycle_end_to_end_fleet(tmp_path):
    """The acceptance scenario: under continuous fleet traffic, a
    continuation-trained candidate passes the gate and hot-swaps with
    zero dropped requests; a gate-rejected candidate and a mid-swap
    injected fault both leave the incumbent serving bit-identical
    predictions; rollback restores the previous version."""
    X, y = _data(seed=20, n=3000)
    base = _train(X[:2000], y[:2000])
    store = ModelStore(str(tmp_path / "store"))
    store.publish("m", base)
    Xq = X[:64]

    with ServingFleet(store_dir=store.dir, n_replicas=2,
                      cache_dir=str(tmp_path / "cache"),
                      warmup_buckets=(64,)) as fleet:
        ref1 = fleet.predict("m", Xq, timeout=120)
        stop = threading.Event()
        done, errs = [0], []

        def traffic():
            while not stop.is_set():
                try:
                    fleet.predict("m", Xq, timeout=120)
                    done[0] += 1
                except BaseException as e:  # pragma: no cover
                    errs.append(repr(e))
                    return

        th = threading.Thread(target=traffic)
        th.start()
        try:
            mgr = LifecycleManager(fleet, "m", config=LifecycleConfig(
                rounds_per_cycle=3,
                checkpoint_dir=str(tmp_path / "ckpt"),
                shadow_fraction=0.25, shadow_min_pairs=2))
            rep = mgr.run_cycle((X[2000:], y[2000:]),
                                eval_window=(X[:2000], y[:2000]))
            assert rep.swapped and rep.candidate_version == 2
            assert rep.shadow["pairs"] >= 2 and rep.shadow["failures"] == 0
            out = fleet.predict("m", Xq, timeout=120)
            assert not np.array_equal(out, ref1)
            for _ in range(3):  # post-swap predictions are bitwise-stable
                np.testing.assert_array_equal(
                    fleet.predict("m", Xq, timeout=120), out)

            # gate-rejected candidate: incumbent (v2 now) keeps its bits
            rej = LifecycleManager(fleet, "m", config=LifecycleConfig(
                rounds_per_cycle=1, gate=GateConfig(min_improvement=1e9)))
            rep2 = rej.run_cycle((X[2000:], y[2000:]))
            assert not rep2.swapped and rep2.decision.reason == "metric"
            np.testing.assert_array_equal(
                fleet.predict("m", Xq, timeout=120), out)

            # mid-swap fault: candidate published + loaded, never activated
            faults.install([{"site": "lifecycle.swap", "kind": "exception"}])
            rep3 = mgr.run_cycle((X[2000:], y[2000:]))
            faults.clear()
            assert not rep3.swapped and rep3.decision.reason == "fault"
            np.testing.assert_array_equal(
                fleet.predict("m", Xq, timeout=120), out)
            assert store.active_version("m") == 2

            # rollback restores the previous version's exact bits
            assert mgr.rollback() == 1
            np.testing.assert_array_equal(
                fleet.predict("m", Xq, timeout=120), ref1)
            assert store.active_version("m") == 1
        finally:
            stop.set()
            th.join(120)
        assert not errs, errs
        assert done[0] > 0  # traffic genuinely flowed through the swaps


# =========================================================================
# Shadow KS distribution gate (PR 11 satellite)


def test_ks_stat_zero_for_identical_and_one_for_disjoint():
    from xgboost_tpu.serving.fleet import _ks_stat

    a = np.linspace(0.0, 1.0, 100)
    assert _ks_stat(a, a.copy()) == 0.0
    assert _ks_stat(np.zeros(50), np.ones(50)) == pytest.approx(1.0)
    # a mild shift moves the statistic strictly between the extremes
    shifted = _ks_stat(a, a + 0.1)
    assert 0.0 < shifted < 1.0


def test_shadow_ks_gate_rejects_drifted_candidate(tmp_path):
    """A candidate whose shadow phase shows KS drift beyond shadow_max_ks
    is rejected (reason "shadow"): retired from the replicas, never
    activated, incumbent untouched — like every other gate half."""

    X, y, st, fleet = _stub_pair(tmp_path)

    drifted = {"pairs": 5, "failures": 0, "mean_div": 0.01,
               "max_div": 0.02, "mean_ks": 0.4, "max_ks": 0.6}
    fleet.shadow_stats = lambda model: dict(drifted)
    real_clear = fleet.clear_shadow

    def clear(model):
        real_clear(model)
        return dict(drifted)

    fleet.clear_shadow = clear
    mgr = LifecycleManager(fleet, "m", config=LifecycleConfig(
        rounds_per_cycle=2, shadow_fraction=0.5, shadow_min_pairs=1,
        shadow_max_ks=0.1))
    rep = mgr.run_cycle((X[2000:], y[2000:]),
                        eval_window=(X[:2000], y[:2000]))
    assert not rep.swapped
    assert rep.decision.reason == "shadow"
    assert rep.shadow["max_ks"] == pytest.approx(0.6)
    assert rep.trace_id  # the cycle is joinable against flight/trace data
    ops = [c[0] for c in fleet.calls]
    # loaded, shadowed, then RETIRED — never activated
    assert ops == ["load", "set_shadow", "clear_shadow", "retire"]
    assert st.active_version("m") == 1  # incumbent still serving
    # the published-but-rejected candidate is inert; a permissive manager
    # afterwards can still swap (nothing is wedged)
    mgr2 = LifecycleManager(fleet, "m", config=LifecycleConfig(
        rounds_per_cycle=2))
    rep2 = mgr2.run_cycle((X[2000:], y[2000:]),
                          eval_window=(X[:2000], y[:2000]))
    assert rep2.swapped


def test_shadow_ks_gate_passes_within_threshold(tmp_path):
    X, y, st, fleet = _stub_pair(tmp_path)
    mgr = LifecycleManager(fleet, "m", config=LifecycleConfig(
        rounds_per_cycle=2, shadow_fraction=0.5, shadow_min_pairs=1,
        shadow_max_ks=0.25))  # stub reports max_ks 0.0
    rep = mgr.run_cycle((X[2000:], y[2000:]),
                        eval_window=(X[:2000], y[:2000]))
    assert rep.swapped and rep.decision.accepted
    assert st.active_version("m") == 2
