import java.util.HashMap;
import java.util.Map;

import ml.dmlc.xgboost_tpu.java.Booster;
import ml.dmlc.xgboost_tpu.java.DMatrix;
import ml.dmlc.xgboost_tpu.java.XGBoost;

/** Train/predict/serialize smoke through the JVM binding (run on a
 * machine with a JDK — see ../README.md). */
public final class Smoke {
  public static void main(String[] args) throws Exception {
    int n = 1000, f = 8;
    java.util.Random rnd = new java.util.Random(1);
    float[] data = new float[n * f];
    float[] label = new float[n];
    for (int i = 0; i < n; ++i) {
      for (int j = 0; j < f; ++j) {
        data[i * f + j] = (float) rnd.nextGaussian();
      }
      if (i % 17 == 0) {
        data[i * f] = Float.NaN;
      }
      label[i] = (!Float.isNaN(data[i * f]) && data[i * f] > 0) ? 1f : 0f;
    }
    try (DMatrix dtrain = new DMatrix(data, n, f)) {
      dtrain.setLabel(label);
      Map<String, Object> params = new HashMap<>();
      params.put("objective", "binary:logistic");
      params.put("max_depth", 4);
      params.put("eta", 0.3);
      params.put("eval_metric", "logloss");
      Map<String, DMatrix> evals = new HashMap<>();
      evals.put("train", dtrain);
      try (Booster booster = XGBoost.train(dtrain, params, 10, evals)) {
        float[] preds = booster.predict(dtrain);
        int err = 0;
        for (int i = 0; i < n; ++i) {
          if ((preds[i] > 0.5f) != (label[i] > 0.5f)) {
            ++err;
          }
        }
        System.out.println("train error: " + (double) err / n);
        if (err > n / 10) {
          throw new AssertionError("model failed to learn");
        }
        byte[] raw = booster.toByteArray("ubj");
        try (Booster loaded = Booster.loadModel(raw)) {
          float[] p2 = loaded.predict(dtrain);
          for (int i = 0; i < n; ++i) {
            if (p2[i] != preds[i]) {
              throw new AssertionError("round-trip mismatch at " + i);
            }
          }
        }
        System.out.println("JVM binding smoke: OK");
      }
    }
  }
}
