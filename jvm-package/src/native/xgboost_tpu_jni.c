/* JNI glue over the xgboost_tpu C ABI (libxtb_capi.so) — the role of the
 * reference's jvm-packages/xgboost4j/src/native/xgboost4j.cpp, written
 * fresh for this ABI.
 *
 * Every entry converts JVM arrays (float[]/double is row-major already —
 * no transpose, unlike R), wraps handles as jlong, and returns the C
 * return code; Java-side XGBoostError carries XGBGetLastError().
 *
 * Build (needs a JDK for jni.h; none ships in this image):
 *   gcc -shared -fPIC -I$JAVA_HOME/include -I$JAVA_HOME/include/linux \
 *       xgboost_tpu_jni.c -L../../../native -lxtb_capi \
 *       -o libxgboost_tpu_jni.so
 * The exact C-ABI call sequence this file makes is pinned by
 * native/jni_glue_seq.c (tests/test_c_api.py::test_jni_glue_sequence), so
 * the contract is CI-verified even without a JDK.
 */
#include <jni.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

typedef void* DMatrixHandle;
typedef void* BoosterHandle;
typedef uint64_t bst_ulong;

extern const char* XGBGetLastError(void);
extern int XGDMatrixCreateFromMat(const float*, bst_ulong, bst_ulong, float,
                                  DMatrixHandle*);
extern int XGDMatrixSetFloatInfo(DMatrixHandle, const char*, const float*,
                                 bst_ulong);
extern int XGDMatrixSetUIntInfo(DMatrixHandle, const char*, const unsigned*,
                                bst_ulong);
extern int XGDMatrixNumRow(DMatrixHandle, bst_ulong*);
extern int XGDMatrixFree(DMatrixHandle);
extern int XGBoosterCreate(const DMatrixHandle[], bst_ulong, BoosterHandle*);
extern int XGBoosterFree(BoosterHandle);
extern int XGBoosterSetParam(BoosterHandle, const char*, const char*);
extern int XGBoosterUpdateOneIter(BoosterHandle, int, DMatrixHandle);
extern int XGBoosterEvalOneIter(BoosterHandle, int, DMatrixHandle[],
                                const char*[], bst_ulong, const char**);
extern int XGBoosterPredict(BoosterHandle, DMatrixHandle, int, unsigned, int,
                            bst_ulong*, const float**);
extern int XGBoosterSaveModelToBuffer(BoosterHandle, const char*, bst_ulong*,
                                      const char**);
extern int XGBoosterLoadModelFromBuffer(BoosterHandle, const void*,
                                        bst_ulong);

#define JNI_SIG(ret, name) \
  JNIEXPORT ret JNICALL Java_ml_dmlc_xgboost_1tpu_java_XGBoostJNI_##name

JNI_SIG(jstring, XGBGetLastError)(JNIEnv* env, jclass cls) {
  return (*env)->NewStringUTF(env, XGBGetLastError());
}

JNI_SIG(jint, XGDMatrixCreateFromMat)(JNIEnv* env, jclass cls,
                                      jfloatArray jdata, jlong nrow,
                                      jlong ncol, jfloat missing,
                                      jlongArray jout) {
  jfloat* data = (*env)->GetFloatArrayElements(env, jdata, NULL);
  DMatrixHandle h = NULL;
  int rc = XGDMatrixCreateFromMat((const float*)data, (bst_ulong)nrow,
                                  (bst_ulong)ncol, missing, &h);
  (*env)->ReleaseFloatArrayElements(env, jdata, data, JNI_ABORT);
  jlong out = (jlong)(intptr_t)h;
  (*env)->SetLongArrayRegion(env, jout, 0, 1, &out);
  return rc;
}

JNI_SIG(jint, XGDMatrixSetFloatInfo)(JNIEnv* env, jclass cls, jlong handle,
                                     jstring jfield, jfloatArray jvec) {
  const char* field = (*env)->GetStringUTFChars(env, jfield, NULL);
  jfloat* vec = (*env)->GetFloatArrayElements(env, jvec, NULL);
  jsize n = (*env)->GetArrayLength(env, jvec);
  int rc = XGDMatrixSetFloatInfo((DMatrixHandle)(intptr_t)handle, field,
                                 (const float*)vec, (bst_ulong)n);
  (*env)->ReleaseFloatArrayElements(env, jvec, vec, JNI_ABORT);
  (*env)->ReleaseStringUTFChars(env, jfield, field);
  return rc;
}

JNI_SIG(jint, XGDMatrixSetUIntInfo)(JNIEnv* env, jclass cls, jlong handle,
                                    jstring jfield, jintArray jvec) {
  const char* field = (*env)->GetStringUTFChars(env, jfield, NULL);
  jint* vec = (*env)->GetIntArrayElements(env, jvec, NULL);
  jsize n = (*env)->GetArrayLength(env, jvec);
  int rc = XGDMatrixSetUIntInfo((DMatrixHandle)(intptr_t)handle, field,
                                (const unsigned*)vec, (bst_ulong)n);
  (*env)->ReleaseIntArrayElements(env, jvec, vec, JNI_ABORT);
  (*env)->ReleaseStringUTFChars(env, jfield, field);
  return rc;
}

JNI_SIG(jint, XGDMatrixNumRow)(JNIEnv* env, jclass cls, jlong handle,
                               jlongArray jout) {
  bst_ulong n = 0;
  int rc = XGDMatrixNumRow((DMatrixHandle)(intptr_t)handle, &n);
  jlong out = (jlong)n;
  (*env)->SetLongArrayRegion(env, jout, 0, 1, &out);
  return rc;
}

JNI_SIG(jint, XGDMatrixFree)(JNIEnv* env, jclass cls, jlong handle) {
  return XGDMatrixFree((DMatrixHandle)(intptr_t)handle);
}

JNI_SIG(jint, XGBoosterCreate)(JNIEnv* env, jclass cls, jlongArray jdmats,
                               jlongArray jout) {
  jsize n = (*env)->GetArrayLength(env, jdmats);
  jlong* dm = (*env)->GetLongArrayElements(env, jdmats, NULL);
  DMatrixHandle* arr =
      (DMatrixHandle*)malloc((n ? n : 1) * sizeof(DMatrixHandle));
  for (jsize i = 0; i < n; ++i) arr[i] = (DMatrixHandle)(intptr_t)dm[i];
  BoosterHandle h = NULL;
  int rc = XGBoosterCreate(arr, (bst_ulong)n, &h);
  free(arr);
  (*env)->ReleaseLongArrayElements(env, jdmats, dm, JNI_ABORT);
  jlong out = (jlong)(intptr_t)h;
  (*env)->SetLongArrayRegion(env, jout, 0, 1, &out);
  return rc;
}

JNI_SIG(jint, XGBoosterFree)(JNIEnv* env, jclass cls, jlong handle) {
  return XGBoosterFree((BoosterHandle)(intptr_t)handle);
}

JNI_SIG(jint, XGBoosterSetParam)(JNIEnv* env, jclass cls, jlong handle,
                                 jstring jname, jstring jval) {
  const char* name = (*env)->GetStringUTFChars(env, jname, NULL);
  const char* val = (*env)->GetStringUTFChars(env, jval, NULL);
  int rc = XGBoosterSetParam((BoosterHandle)(intptr_t)handle, name, val);
  (*env)->ReleaseStringUTFChars(env, jval, val);
  (*env)->ReleaseStringUTFChars(env, jname, name);
  return rc;
}

JNI_SIG(jint, XGBoosterUpdateOneIter)(JNIEnv* env, jclass cls, jlong handle,
                                      jint iter, jlong dtrain) {
  return XGBoosterUpdateOneIter((BoosterHandle)(intptr_t)handle, iter,
                                (DMatrixHandle)(intptr_t)dtrain);
}

JNI_SIG(jint, XGBoosterEvalOneIter)(JNIEnv* env, jclass cls, jlong handle,
                                    jint iter, jlongArray jdmats,
                                    jobjectArray jnames,
                                    jobjectArray jout) {
  jsize n = (*env)->GetArrayLength(env, jdmats);
  jlong* dm = (*env)->GetLongArrayElements(env, jdmats, NULL);
  DMatrixHandle* arr =
      (DMatrixHandle*)malloc((n ? n : 1) * sizeof(DMatrixHandle));
  const char** nm = (const char**)malloc((n ? n : 1) * sizeof(char*));
  jstring* js = (jstring*)malloc((n ? n : 1) * sizeof(jstring));
  for (jsize i = 0; i < n; ++i) {
    arr[i] = (DMatrixHandle)(intptr_t)dm[i];
    js[i] = (jstring)(*env)->GetObjectArrayElement(env, jnames, i);
    nm[i] = (*env)->GetStringUTFChars(env, js[i], NULL);
  }
  const char* msg = NULL;
  int rc = XGBoosterEvalOneIter((BoosterHandle)(intptr_t)handle, iter, arr,
                                nm, (bst_ulong)n, &msg);
  for (jsize i = 0; i < n; ++i)
    (*env)->ReleaseStringUTFChars(env, js[i], nm[i]);
  free(js);
  free(nm);
  free(arr);
  (*env)->ReleaseLongArrayElements(env, jdmats, dm, JNI_ABORT);
  (*env)->SetObjectArrayElement(
      env, jout, 0, (*env)->NewStringUTF(env, msg ? msg : ""));
  return rc;
}

JNI_SIG(jint, XGBoosterPredict)(JNIEnv* env, jclass cls, jlong handle,
                                jlong dmat, jint option_mask,
                                jint ntree_limit, jobjectArray jout) {
  bst_ulong len = 0;
  const float* res = NULL;
  int rc = XGBoosterPredict((BoosterHandle)(intptr_t)handle,
                            (DMatrixHandle)(intptr_t)dmat, option_mask,
                            (unsigned)ntree_limit, 0, &len, &res);
  if (rc == 0) {
    jfloatArray arr = (*env)->NewFloatArray(env, (jsize)len);
    (*env)->SetFloatArrayRegion(env, arr, 0, (jsize)len, res);
    (*env)->SetObjectArrayElement(env, jout, 0, arr);
  }
  return rc;
}

JNI_SIG(jint, XGBoosterSaveModelToBuffer)(JNIEnv* env, jclass cls,
                                          jlong handle, jstring jformat,
                                          jobjectArray jout) {
  const char* format = (*env)->GetStringUTFChars(env, jformat, NULL);
  bst_ulong len = 0;
  const char* buf = NULL;
  int rc = XGBoosterSaveModelToBuffer((BoosterHandle)(intptr_t)handle,
                                      format, &len, &buf);
  (*env)->ReleaseStringUTFChars(env, jformat, format);
  if (rc == 0) {
    jbyteArray arr = (*env)->NewByteArray(env, (jsize)len);
    (*env)->SetByteArrayRegion(env, arr, 0, (jsize)len,
                               (const jbyte*)buf);
    (*env)->SetObjectArrayElement(env, jout, 0, arr);
  }
  return rc;
}

JNI_SIG(jint, XGBoosterLoadModelFromBuffer)(JNIEnv* env, jclass cls,
                                            jlong handle, jbyteArray jbuf) {
  jbyte* buf = (*env)->GetByteArrayElements(env, jbuf, NULL);
  jsize n = (*env)->GetArrayLength(env, jbuf);
  int rc = XGBoosterLoadModelFromBuffer((BoosterHandle)(intptr_t)handle,
                                        buf, (bst_ulong)n);
  (*env)->ReleaseByteArrayElements(env, jbuf, buf, JNI_ABORT);
  return rc;
}
