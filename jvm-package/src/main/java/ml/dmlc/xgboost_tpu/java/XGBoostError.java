package ml.dmlc.xgboost_tpu.java;

/** Error carrying XGBGetLastError() (xgboost4j.java.XGBoostError role). */
public class XGBoostError extends Exception {
  public XGBoostError(String message) {
    super(message);
  }

  static void check(int ret) throws XGBoostError {
    if (ret != 0) {
      throw new XGBoostError(XGBoostJNI.XGBGetLastError());
    }
  }
}
