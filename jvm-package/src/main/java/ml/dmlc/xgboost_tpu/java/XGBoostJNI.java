package ml.dmlc.xgboost_tpu.java;

/**
 * Raw JNI surface over the xgboost_tpu C ABI (the reference's
 * xgboost4j.java.XGBoostJNI role).  All methods return the C ABI status
 * code; callers wrap non-zero codes in {@link XGBoostError} with
 * {@link #XGBGetLastError()}.
 *
 * Native library: libxgboost_tpu_jni.so (see src/native/xgboost_tpu_jni.c
 * for the build line; requires a JDK and the prebuilt libxtb_capi.so).
 */
final class XGBoostJNI {
  static {
    System.loadLibrary("xgboost_tpu_jni");
  }

  private XGBoostJNI() {}

  static native String XGBGetLastError();

  static native int XGDMatrixCreateFromMat(float[] data, long nrow,
                                           long ncol, float missing,
                                           long[] out);

  static native int XGDMatrixSetFloatInfo(long handle, String field,
                                          float[] values);

  static native int XGDMatrixSetUIntInfo(long handle, String field,
                                         int[] values);

  static native int XGDMatrixNumRow(long handle, long[] out);

  static native int XGDMatrixFree(long handle);

  static native int XGBoosterCreate(long[] dmats, long[] out);

  static native int XGBoosterFree(long handle);

  static native int XGBoosterSetParam(long handle, String name, String value);

  static native int XGBoosterUpdateOneIter(long handle, int iter,
                                           long dtrain);

  static native int XGBoosterEvalOneIter(long handle, int iter, long[] dmats,
                                         String[] names, String[] out);

  static native int XGBoosterPredict(long handle, long dmat, int optionMask,
                                     int ntreeLimit, float[][] out);

  static native int XGBoosterSaveModelToBuffer(long handle, String format,
                                               byte[][] out);

  static native int XGBoosterLoadModelFromBuffer(long handle, byte[] buf);

  static native int XGBoosterSetAttr(long handle, String key, String value);

  static native int XGBoosterGetAttr(long handle, String key, String[] out);
}
