package ml.dmlc.xgboost_tpu.java;

/**
 * Data container (reference surface: xgboost4j.java.DMatrix, backed by the
 * same XGDMatrix* C entries).  Row-major float input; NaN = missing.
 */
public class DMatrix implements AutoCloseable {
  long handle;

  public DMatrix(float[] data, int nrow, int ncol) throws XGBoostError {
    this(data, nrow, ncol, Float.NaN);
  }

  public DMatrix(float[] data, int nrow, int ncol, float missing)
      throws XGBoostError {
    if (data.length != (long) nrow * ncol) {
      throw new IllegalArgumentException(
          "data.length " + data.length + " != nrow*ncol " + (long) nrow * ncol);
    }
    long[] out = new long[1];
    XGBoostError.check(
        XGBoostJNI.XGDMatrixCreateFromMat(data, nrow, ncol, missing, out));
    handle = out[0];
  }

  public void setLabel(float[] labels) throws XGBoostError {
    XGBoostError.check(
        XGBoostJNI.XGDMatrixSetFloatInfo(handle, "label", labels));
  }

  public void setWeight(float[] weights) throws XGBoostError {
    XGBoostError.check(
        XGBoostJNI.XGDMatrixSetFloatInfo(handle, "weight", weights));
  }

  public void setBaseMargin(float[] margin) throws XGBoostError {
    XGBoostError.check(
        XGBoostJNI.XGDMatrixSetFloatInfo(handle, "base_margin", margin));
  }

  public void setGroup(int[] group) throws XGBoostError {
    XGBoostError.check(
        XGBoostJNI.XGDMatrixSetUIntInfo(handle, "group", group));
  }

  public long rowNum() throws XGBoostError {
    long[] out = new long[1];
    XGBoostError.check(XGBoostJNI.XGDMatrixNumRow(handle, out));
    return out[0];
  }

  @Override
  public void close() {
    if (handle != 0) {
      XGBoostJNI.XGDMatrixFree(handle);
      handle = 0;
    }
  }
}
