package ml.dmlc.xgboost_tpu.java;

import java.util.Map;

/**
 * Trained model handle (reference surface: xgboost4j.java.Booster over the
 * same XGBooster* C entries).
 */
public class Booster implements AutoCloseable {
  long handle;

  Booster(long handle) {
    this.handle = handle;
  }

  public static Booster create(Map<String, Object> params, DMatrix[] cache)
      throws XGBoostError {
    long[] dmats = new long[cache == null ? 0 : cache.length];
    for (int i = 0; i < dmats.length; ++i) {
      dmats[i] = cache[i].handle;
    }
    long[] out = new long[1];
    XGBoostError.check(XGBoostJNI.XGBoosterCreate(dmats, out));
    Booster b = new Booster(out[0]);
    try {
      if (params != null) {
        for (Map.Entry<String, Object> e : params.entrySet()) {
          b.setParam(e.getKey(), String.valueOf(e.getValue()));
        }
      }
      return b;
    } catch (XGBoostError | RuntimeException e) {
      b.close();
      throw e;
    }
  }

  public void setParam(String name, String value) throws XGBoostError {
    XGBoostError.check(XGBoostJNI.XGBoosterSetParam(handle, name, value));
  }

  public void update(DMatrix dtrain, int iter) throws XGBoostError {
    XGBoostError.check(
        XGBoostJNI.XGBoosterUpdateOneIter(handle, iter, dtrain.handle));
  }

  public String evalSet(DMatrix[] evalMatrixs, String[] evalNames, int iter)
      throws XGBoostError {
    long[] dmats = new long[evalMatrixs.length];
    for (int i = 0; i < dmats.length; ++i) {
      dmats[i] = evalMatrixs[i].handle;
    }
    String[] out = new String[1];
    XGBoostError.check(
        XGBoostJNI.XGBoosterEvalOneIter(handle, iter, dmats, evalNames, out));
    return out[0];
  }

  public float[] predict(DMatrix dmat) throws XGBoostError {
    return predict(dmat, false, 0);
  }

  public float[] predict(DMatrix dmat, boolean outputMargin, int ntreeLimit)
      throws XGBoostError {
    float[][] out = new float[1][];
    XGBoostError.check(XGBoostJNI.XGBoosterPredict(
        handle, dmat.handle, outputMargin ? 1 : 0, ntreeLimit, out));
    return out[0];
  }

  public void setAttr(String key, String value) throws XGBoostError {
    XGBoostError.check(XGBoostJNI.XGBoosterSetAttr(handle, key, value));
  }

  /** null when the attribute was never set (reference getAttr contract). */
  public String getAttr(String key) throws XGBoostError {
    String[] out = new String[1];
    XGBoostError.check(XGBoostJNI.XGBoosterGetAttr(handle, key, out));
    return out[0];
  }

  /** Serialize to ubj/json bytes (the byte-array model exchange the JVM
   * ecosystem uses for spark checkpointing). */
  public byte[] toByteArray(String format) throws XGBoostError {
    byte[][] out = new byte[1][];
    XGBoostError.check(
        XGBoostJNI.XGBoosterSaveModelToBuffer(handle, format, out));
    return out[0];
  }

  public static Booster loadModel(byte[] buf) throws XGBoostError {
    long[] out = new long[1];
    XGBoostError.check(XGBoostJNI.XGBoosterCreate(new long[0], out));
    Booster b = new Booster(out[0]);
    try {
      XGBoostError.check(
          XGBoostJNI.XGBoosterLoadModelFromBuffer(b.handle, buf));
      return b;
    } catch (XGBoostError | RuntimeException e) {
      b.close();
      throw e;
    }
  }

  @Override
  public void close() {
    if (handle != 0) {
      XGBoostJNI.XGBoosterFree(handle);
      handle = 0;
    }
  }
}
