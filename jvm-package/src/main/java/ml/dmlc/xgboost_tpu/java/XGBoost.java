package ml.dmlc.xgboost_tpu.java;

import java.util.Map;

/**
 * Training entry points (reference surface: xgboost4j.java.XGBoost.train).
 */
public final class XGBoost {
  private XGBoost() {}

  public static Booster train(DMatrix dtrain, Map<String, Object> params,
                              int numRounds, Map<String, DMatrix> evals)
      throws XGBoostError {
    return train(dtrain, params, numRounds, evals, 0, null);
  }

  /**
   * Train with early stopping (reference surface: xgboost4j XGBoost.train
   * earlyStoppingRounds): stops when the LAST metric on the LAST evals
   * entry has not improved for earlyStoppingRounds rounds; the best round
   * lands in the "best_iteration" / "best_score" booster attrs (0-based
   * round id, the convention shared with the Python and R bindings).
   * maximize == null auto-detects from the metric name (auc/map/ndcg/pre
   * maximize, everything else — including mape — minimizes).
   */
  public static Booster train(DMatrix dtrain, Map<String, Object> params,
                              int numRounds, Map<String, DMatrix> evals,
                              int earlyStoppingRounds, Boolean maximize)
      throws XGBoostError {
    if (earlyStoppingRounds > 0 && (evals == null || evals.isEmpty())) {
      throw new IllegalArgumentException(
          "earlyStoppingRounds needs at least one evals entry");
    }
    Booster booster = Booster.create(params, new DMatrix[] {dtrain});
    try {
      DMatrix[] evalMats = new DMatrix[evals == null ? 0 : evals.size()];
      String[] evalNames = new String[evalMats.length];
      int i = 0;
      if (evals != null) {
        for (Map.Entry<String, DMatrix> e : evals.entrySet()) {
          evalNames[i] = e.getKey();
          evalMats[i] = e.getValue();
          ++i;
        }
      }
      double bestScore = Double.NaN;
      int bestIter = -1;
      for (int iter = 0; iter < numRounds; ++iter) {
        booster.update(dtrain, iter);
        if (evalMats.length > 0) {
          String msg = booster.evalSet(evalMats, evalNames, iter);
          System.out.println(msg);
          if (earlyStoppingRounds > 0) {
            // "[i]\tname-metric:value\t..." — track the final field
            String[] parts = msg.trim().split("[\t ]+");
            String last = parts[parts.length - 1];
            int colon = last.lastIndexOf(':');
            double score = Double.parseDouble(last.substring(colon + 1));
            String metric = last.substring(0, colon);
            String bare = metric.substring(metric.lastIndexOf('-') + 1);
            boolean mx = maximize != null ? maximize
                : (bare.matches("^(auc|aucpr|map|ndcg|pre).*")
                   && !bare.startsWith("mape"));
            boolean better = Double.isNaN(bestScore)
                || (mx ? score > bestScore : score < bestScore);
            if (better) {
              bestScore = score;
              bestIter = iter;
            } else if (iter - bestIter >= earlyStoppingRounds) {
              break;
            }
          }
        }
      }
      if (bestIter >= 0) {
        booster.setAttr("best_iteration", String.valueOf(bestIter));
        booster.setAttr("best_score", String.valueOf(bestScore));
      }
      return booster;
    } catch (XGBoostError | RuntimeException e) {
      booster.close(); // don't leak the native handle on a failed train
      throw e;
    }
  }
}
