package ml.dmlc.xgboost_tpu.java;

import java.util.Map;

/**
 * Training entry points (reference surface: xgboost4j.java.XGBoost.train).
 */
public final class XGBoost {
  private XGBoost() {}

  public static Booster train(DMatrix dtrain, Map<String, Object> params,
                              int numRounds, Map<String, DMatrix> evals)
      throws XGBoostError {
    Booster booster = Booster.create(params, new DMatrix[] {dtrain});
    try {
      DMatrix[] evalMats = new DMatrix[evals == null ? 0 : evals.size()];
      String[] evalNames = new String[evalMats.length];
      int i = 0;
      if (evals != null) {
        for (Map.Entry<String, DMatrix> e : evals.entrySet()) {
          evalNames[i] = e.getKey();
          evalMats[i] = e.getValue();
          ++i;
        }
      }
      for (int iter = 0; iter < numRounds; ++iter) {
        booster.update(dtrain, iter);
        if (evalMats.length > 0) {
          System.out.println(booster.evalSet(evalMats, evalNames, iter));
        }
      }
      return booster;
    } catch (XGBoostError | RuntimeException e) {
      booster.close(); // don't leak the native handle on a failed train
      throw e;
    }
  }
}
